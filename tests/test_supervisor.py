"""Chaos kill matrix for the elastic training supervisor (ISSUE 14).

Matrix: kill {trainer, PS shard, graph shard} at {mid-step,
mid-checkpoint, mid-push}. The acceptance bar is exact:

- a killed trainer resumes to BIT-IDENTICAL final params vs the
  uninterrupted seeded run (same shuffles, same RNG stream, no
  re-trained or skipped batches);
- journaled PS/graph pushes apply exactly once under ack loss and
  post-recovery replay — dedup hits equal the injected replays, and
  the table state shows zero double-applies.
"""
import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.graph_service import (GraphPyClient,
                                                  GraphPyServer)
from paddle_tpu.distributed.ps.embedding_service import (EmbeddingClient,
                                                         EmbeddingServer)
from paddle_tpu.distributed.resilience import RetryPolicy
from paddle_tpu.distributed.supervisor import (PreemptionWatcher,
                                               PushJournal, ShardSpec,
                                               ShardSupervisor,
                                               SupervisorAbort,
                                               TrainingSupervisor)
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.hapi.model import Model
from paddle_tpu.io import Dataset
from paddle_tpu.monitor.registry import MetricRegistry
from paddle_tpu.testing import chaos


# ---------------------------------------------------------------- trainer

class _ToyData(Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(7)
        self.x = rng.randn(n, 4).astype(np.float32)
        self.y = rng.randn(n, 1).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _build_model():
    paddle.seed(1234)
    np.random.seed(99)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss())
    return m


def _params(m):
    return {k: np.asarray(v._data if hasattr(v, '_data') else v)
            for k, v in m.network.state_dict().items()}


def _fit(m, **kw):
    return m.fit(_ToyData(), batch_size=4, epochs=3, shuffle=True,
                 verbose=0, **kw)


@pytest.fixture(scope='module')
def reference_params():
    """Final params of the uninterrupted seeded 3-epoch run — the
    bit-identity oracle for every trainer-kill scenario."""
    m = _build_model()
    _fit(m)
    return _params(m)


def _assert_bit_identical(got, ref):
    for k in ref:
        assert np.array_equal(ref[k], got[k]), \
            'param %s diverged (max |d|=%g)' % (
                k, np.abs(ref[k] - got[k]).max())


class _KillAt(Callback):
    """Simulated hard kill: raises out of the fit loop at the Nth
    completed batch, before the supervisor's on_step checkpointing."""

    def __init__(self, at, exc=KeyboardInterrupt):
        self.at = at
        self.exc = exc
        self.seen = 0

    def on_train_batch_end(self, step, logs=None):
        self.seen += 1
        if self.seen == self.at:
            raise self.exc('simulated kill at batch %d' % self.at)


def test_trainer_killed_mid_step_resumes_bit_identical(
        tmp_path, reference_params):
    ckpt = str(tmp_path / 'ckpt')
    m1 = _build_model()
    sup1 = TrainingSupervisor(ckpt, save_every_steps=5)
    with pytest.raises(KeyboardInterrupt):
        _fit(m1, supervisor=sup1, callbacks=[_KillAt(13)])
    assert sup1.last_saved_step == 10

    m2 = _build_model()
    np.random.seed(555)   # wrong seed on purpose: the cursor must win
    sup2 = TrainingSupervisor(ckpt, save_every_steps=5)
    _fit(m2, supervisor=sup2)
    _assert_bit_identical(_params(m2), reference_params)


@pytest.mark.parametrize('point', ['pre_rename', 'pre_manifest'])
def test_trainer_killed_mid_checkpoint_falls_back(tmp_path, point,
                                                  reference_params):
    """The writer dies INSIDE the step-8 checkpoint (both torn states:
    before the rename, and between rename and manifest). Restart must
    fall back to the intact step-4 snapshot and still reach the
    bit-identical final state."""
    ckpt = str(tmp_path / 'ckpt')
    m1 = _build_model()
    sup1 = TrainingSupervisor(ckpt, save_every_steps=4)
    with chaos.crash_io_save(point, path_substr='step_8') as fault:
        with pytest.raises(chaos.WriterKilled):
            _fit(m1, supervisor=sup1)
    assert fault.fired == 1
    if point == 'pre_manifest':
        # data file landed, manifest did not: present but torn
        assert os.path.exists(os.path.join(ckpt, 'step_8.ckpt'))
    else:
        assert not os.path.exists(os.path.join(ckpt, 'step_8.ckpt'))

    m2 = _build_model()
    sup2 = TrainingSupervisor(ckpt, save_every_steps=4)
    cursor = sup2.restore(m2)
    assert cursor.global_step == 4        # torn step-8 skipped
    _fit(m2, supervisor=sup2)
    _assert_bit_identical(_params(m2), reference_params)


def test_sigterm_preemption_checkpoints_and_resumes(tmp_path,
                                                    reference_params):
    """Real SIGTERM: the watcher's handler flags it, on_step writes an
    urgent checkpoint and stops the run cleanly; the next run resumes
    to the bit-identical final state."""
    ckpt = str(tmp_path / 'ckpt')

    class _Sigterm(Callback):
        def __init__(self):
            self.seen = 0

        def on_train_batch_end(self, step, logs=None):
            self.seen += 1
            if self.seen == 7:
                os.kill(os.getpid(), signal.SIGTERM)

    m1 = _build_model()
    with PreemptionWatcher() as watcher:
        sup1 = TrainingSupervisor(ckpt, watcher=watcher)
        _fit(m1, supervisor=sup1, callbacks=[_Sigterm()])
    assert m1.stop_training
    assert sup1.last_saved_step == 7      # urgent, not periodic

    m2 = _build_model()
    sup2 = TrainingSupervisor(ckpt)
    _fit(m2, supervisor=sup2)
    _assert_bit_identical(_params(m2), reference_params)


# ---------------------------------------------------------------- PS shard

def _make_embedding_server(port=0):
    srv = EmbeddingServer(port=port)
    srv.create_table(0, dim=4, optimizer='sgd', lr=1.0)
    srv.start()
    return srv


def test_ps_push_ack_lost_dedups_exactly_once():
    """Mid-push kill from the client's view: the reply is lost AFTER the
    server applied the write. The journaled retry must be deduplicated —
    dedup hits equal the injected drops, and the table shows exactly one
    application."""
    srv = _make_embedding_server()
    try:
        journal = PushJournal('trainer-0', registry=MetricRegistry())
        cli = EmbeddingClient(endpoints=['127.0.0.1:%d' % srv.port],
                              journal=journal)
        ids = [1, 2, 3]
        base = cli.pull(0, ids)
        grad = np.ones((3, 4), np.float32)
        with chaos.drop_connections(endpoint=str(srv.port), point='recv',
                                    times=1) as fault:
            cli.push(0, ids, grad)
        assert fault.fired == 1
        assert journal.dedup_hits == fault.fired   # retry was dedup'd
        got = cli.pull(0, ids)
        # lr=1.0 SGD: exactly one application is base - grad; a double
        # apply would be base - 2*grad
        assert np.allclose(got, base - grad)
    finally:
        srv.stop()


def test_ps_shard_killed_recovers_exactly_once(tmp_path):
    """PS shard hard-killed after a snapshot barrier plus one extra
    journaled push. Recovery = restart + restore + replay; the replay
    applies only the post-snapshot entry, a second (spurious) replay
    dedups everything, and the final table state equals the pre-kill
    state bit for bit."""
    reg = MetricRegistry()
    srv = _make_embedding_server()
    port = srv.port
    holder = {'srv': srv}

    def restart():
        holder['srv'] = _make_embedding_server(port)

    try:
        journal = PushJournal('trainer-0', registry=reg)
        cli = EmbeddingClient(endpoints=['127.0.0.1:%d' % port],
                              journal=journal)
        ids = [1, 2, 3]
        cli.pull(0, ids)
        cli.push(0, ids, np.ones((3, 4), np.float32))      # seq 1

        sup = ShardSupervisor(miss_threshold=1, restart_budget=3,
                              ping_timeout=0.5, registry=reg)
        sup.add_shard(ShardSpec('emb0', '127.0.0.1:%d' % port, role='ps',
                                restart=restart,
                                snapshot_dir=str(tmp_path / 'snaps'),
                                clients=(cli,)))
        sup.snapshot_all()
        assert len(journal) == 0      # barrier trims the covered prefix

        cli.push(0, ids, np.ones((3, 4), np.float32))      # seq 2
        want = cli.pull(0, ids)

        chaos.kill_server(holder['srv'])
        assert sup.poll() == {'emb0': True}   # detect + recover inline
        assert sup.alive('emb0')

        got = cli.pull(0, ids)
        assert np.array_equal(want, got)      # zero double-applies
        # recovery replayed exactly the one post-snapshot entry, fresh
        assert journal.replayed == 1
        assert journal.dedup_hits == 0

        # a spurious second replay must be entirely dedup'd
        replayed, dedup = cli.replay_journal()
        assert (replayed, dedup) == (1, 1)
        assert journal.dedup_hits == 1        # == injected replays
        assert np.array_equal(cli.pull(0, ids), want)

        fams = {f.name: f for f in reg.collect()}
        assert fams['supervisor_restarts_total'].labels('ps').value() == 1
        count, total = fams['supervisor_recover_seconds'].value()
        assert count == 1 and total > 0
        assert fams['supervisor_shards_alive'].value() == 1
    finally:
        try:
            holder['srv'].stop()
        except Exception:
            pass


@pytest.mark.filterwarnings(
    'ignore::pytest.PytestUnhandledThreadExceptionWarning')
def test_ps_snapshot_killed_mid_write_keeps_journal(tmp_path):
    """Shard killed mid-CHECKPOINT: the snapshot writer dies before the
    manifest. snapshot_all must propagate the failure WITHOUT trimming
    the journal, and recovery must fall back to the older intact
    snapshot + full journal replay — state still exact."""
    reg = MetricRegistry()
    srv = _make_embedding_server()
    port = srv.port
    holder = {'srv': srv}

    def restart():
        holder['srv'] = _make_embedding_server(port)

    try:
        journal = PushJournal('trainer-0', registry=reg)
        cli = EmbeddingClient(endpoints=['127.0.0.1:%d' % port],
                              journal=journal)
        ids = [1, 2, 3]
        cli.pull(0, ids)
        cli.push(0, ids, np.ones((3, 4), np.float32))

        sup = ShardSupervisor(miss_threshold=1, restart_budget=3,
                              ping_timeout=0.5, registry=reg)
        sup.add_shard(ShardSpec('emb0', '127.0.0.1:%d' % port, role='ps',
                                restart=restart,
                                snapshot_dir=str(tmp_path / 'snaps'),
                                clients=(cli,)))
        sup.snapshot_all()                    # intact snap 1, trims seq 1
        cli.push(0, ids, np.ones((3, 4), np.float32))

        with chaos.crash_io_save('pre_manifest', path_substr='emb0_snap'):
            with pytest.raises(Exception):
                sup.snapshot_all()            # torn snap 2, server died
        assert len(journal) == 1              # NOT trimmed

        want = cli.pull(0, ids)
        chaos.kill_server(holder['srv'])
        sup.poll()
        assert sup.alive('emb0')
        # torn snap 2 skipped -> snap 1 restored -> journal replayed
        assert np.array_equal(cli.pull(0, ids), want)
        assert journal.replayed == 1
    finally:
        try:
            holder['srv'].stop()
        except Exception:
            pass


def test_escalation_aborts_after_restart_budget(tmp_path):
    """No restart hook can bring the shard back: the ladder must walk
    restart -> abort, raise SupervisorAbort, and count the stages."""
    reg = MetricRegistry()
    srv = _make_embedding_server()
    port = srv.port
    sup = ShardSupervisor(miss_threshold=1, restart_budget=2,
                          ping_timeout=0.2, registry=reg,
                          backoff=RetryPolicy(base_delay=0.01,
                                              max_delay=0.02, jitter=0.0))
    sup.add_shard(ShardSpec('emb0', '127.0.0.1:%d' % port, role='ps',
                            restart=None,
                            snapshot_dir=str(tmp_path / 'snaps')))
    chaos.kill_server(srv)
    with pytest.raises(SupervisorAbort):
        sup.poll()
    assert not sup.alive('emb0')
    fams = {f.name: f for f in reg.collect()}
    esc = fams['supervisor_escalations_total']
    assert esc.labels('restart').value() == 1
    assert esc.labels('abort').value() == 1
    assert fams['supervisor_restarts_total'].labels('ps').value() == 0


# ---------------------------------------------------------------- graph

def test_graph_shard_killed_recovers_exactly_once(tmp_path):
    """Graph shard variant of the kill matrix: oplog snapshot + journal
    replay rebuild the store, ack-lost retries dedup, degrees stay
    exact (no double-added edges)."""
    reg = MetricRegistry()
    srv = GraphPyServer(rank=0, port=0)
    srv.start_server()
    port = srv.port
    holder = {'srv': srv}

    def restart():
        s = GraphPyServer(rank=0, port=port)
        s.start_server()
        holder['srv'] = s

    try:
        journal = PushJournal('trainer-g', registry=reg)
        cli = GraphPyClient(endpoints=['127.0.0.1:%d' % port],
                            journal=journal)
        # mid-push ack loss on a journaled add_edges: retry dedups
        with chaos.drop_connections(endpoint=str(port), point='recv',
                                    times=1) as fault:
            cli.add_edges('default', [1, 2, 3], [4, 5, 6])
        assert fault.fired == 1
        assert journal.dedup_hits == fault.fired
        deg = cli.get_degree('default', [1, 2, 3])
        assert list(deg) == [1, 1, 1]         # not double-added

        sup = ShardSupervisor(miss_threshold=1, restart_budget=3,
                              ping_timeout=0.5, registry=reg)
        sup.add_shard(ShardSpec('graph0', '127.0.0.1:%d' % port,
                                role='graph', restart=restart,
                                snapshot_dir=str(tmp_path / 'gsnaps'),
                                clients=(cli,)))
        sup.snapshot_all()
        assert len(journal) == 0
        cli.add_edges('default', [7], [8])     # post-snapshot entry

        chaos.kill_server(holder['srv'])
        sup.poll()
        assert sup.alive('graph0')
        deg = cli.get_degree('default', [1, 2, 3, 7])
        assert list(deg) == [1, 1, 1, 1]
        assert journal.replayed == 1

        fams = {f.name: f for f in reg.collect()}
        assert fams['supervisor_restarts_total'].labels(
            'graph').value() == 1
    finally:
        try:
            holder['srv'].stop_server()
        except Exception:
            pass


def test_no_leaked_faults():
    assert chaos.active_faults() == 0
