"""ASP 2:4 sparsity + tree index tests (reference patterns:
fluid/contrib/sparsity tests test_asp_*.py; index_dataset
test_index_dataset.py / index_wrapper tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import asp


def test_create_mask_1d_two_four():
    rng = np.random.RandomState(0)
    w = rng.standard_normal((16, 32)).astype(np.float32)
    mask = asp.create_mask(w, 'mask_1d', n=2, m=4)
    assert asp.check_mask_1d(w * mask, 2, 4)
    assert asp.calculate_density(mask) == 0.5
    # the kept entries are the largest-|w| two of each group of 4
    groups_w = np.abs(w).reshape(-1, 4)
    groups_m = mask.reshape(-1, 4)
    for gw, gm in zip(groups_w, groups_m):
        kept = set(np.flatnonzero(gm))
        top2 = set(np.argsort(-gw)[:2])
        assert kept == top2


def test_create_mask_2d():
    rng = np.random.RandomState(1)
    w = rng.standard_normal((8, 8)).astype(np.float32)
    mask = asp.create_mask(w, 'mask_2d_greedy', n=2, m=4)
    assert asp.check_mask_2d(w * mask, 2, 4)
    assert 0.3 <= asp.calculate_density(mask) <= 0.5


@pytest.mark.slow
def test_prune_model_and_decorated_step_preserves_sparsity():
    paddle.seed(5)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    masks = asp.prune_model(model, n=2, m=4)
    assert len(masks) == 2
    for layer in (model[0], model[2]):
        assert asp.check_mask_1d(np.asarray(layer.weight._data), 2, 4)

    opt = asp.decorate(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)).astype(np.int64))
    for _ in range(3):
        loss = nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    # sparsity pattern must survive optimizer updates
    for layer in (model[0], model[2]):
        w = np.asarray(layer.weight._data)
        assert asp.check_mask_1d(w, 2, 4)
        assert np.count_nonzero(w) > 0


def test_excluded_layers():
    paddle.seed(6)
    model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
    asp.set_excluded_layers(['0'])
    try:
        helper = asp.ASPHelper()
        masks = helper.prune_model(model)
        assert len(masks) == 1
    finally:
        asp.reset_excluded_layers()


def test_tree_index_build_and_queries(tmp_path):
    from paddle_tpu.distributed.index_dataset import TreeIndex, IndexWrapper
    items = [100, 101, 102, 103, 104]
    tree = TreeIndex.from_items(items, branch=2)
    assert tree.height() == 4  # 8-leaf complete binary tree
    assert sorted(tree.get_all_leafs()) == items
    # travel path ends at root code 0
    path = tree.get_travel_codes(103)
    assert path[-1] == 0 and len(path) == tree.height()
    # ancestors at level 1 are codes 1 or 2
    anc = tree.get_ancestor_codes(items, 1)
    assert set(anc) <= {1, 2}
    pi = tree.get_pi_relation([100], 2)
    assert 100 in pi

    p = str(tmp_path / 'tree.npz')
    tree.save(p)
    wrapper = IndexWrapper()
    wrapper.insert_tree_index('t', p)
    t2 = wrapper.get_tree_index('t')
    assert t2.total_node_nums() == tree.total_node_nums()
    assert sorted(t2.get_all_leafs()) == items
    with pytest.raises(KeyError):
        wrapper.get_tree_index('nope')


def test_layerwise_sampler_rows():
    from paddle_tpu.distributed.index_dataset import (TreeIndex,
                                                      LayerWiseSampler)
    tree = TreeIndex.from_items(list(range(8)), branch=2)
    sampler = LayerWiseSampler(tree, layer_sample_counts=[1, 2, 3], seed=0)
    rows = sampler.sample([[7, 7]], [3])
    pos = [r for r in rows if r[2] == 1]
    neg = [r for r in rows if r[2] == 0]
    # one positive per non-root travel level
    assert len(pos) == tree.height() - 1
    assert len(neg) >= len(pos)
    # positives are the ancestors' ids of item 3
    codes = tree.get_travel_codes(3)[:-1]
    pos_ids = {r[1] for r in pos}
    assert pos_ids == {tree._code_to_id[c] for c in codes}


def test_beam_search_sampler_finds_best_leaf():
    from paddle_tpu.distributed.index_dataset import (TreeIndex,
                                                      BeamSearchSampler)
    items = list(range(16))
    tree = TreeIndex.from_items(items, branch=2)
    target = 11

    def score(user, nid):
        # score favors nodes on the target's path: simulate a learned model
        if nid == target:
            return 10.0
        path_ids = {tree._code_to_id[c]
                    for c in tree.get_travel_codes(target)}
        return 5.0 if nid in path_ids else float(-abs(hash(nid)) % 100) / 100
    sampler = BeamSearchSampler(tree, beam_size=2)
    result = sampler.sample([1, 2], score)
    assert target in result
