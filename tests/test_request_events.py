"""Request-centric observability (paddle_tpu/monitor/events.py +
tracing.TraceRetention + per-tenant attribution through the serving
stack).

The load-bearing contracts:
  1. EXACTLY one canonical wide event per serving request — engine-
     direct or gateway-fronted, failed-over or not — carrying the full
     schema (REQUEST_EVENT_FIELDS);
  2. per-request kv_page_seconds on the slot engine sum EXACTLY to the
     allocator's pool-occupancy integral (same clock, same timestamps);
  3. chaos oracle: N failovers mean N wide events with failovers=N and
     N failover-retained span trees, each retrievable from tail
     retention by the wide event's trace_id;
  4. disabled paths cost one attribute load + branch;
  5. tenant label cardinality is bounded by construction;
  6. the gateway's _ttfts snapshot is safe under concurrent mutation
     (the slo_burn_rate deque race regression).
"""
import collections
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.monitor import MetricsServer
from paddle_tpu.monitor.events import (FIELD_NAMES, RequestLog,
                                       TenantLabeler, event_line,
                                       parse_event_lines,
                                       set_default_request_log)
from paddle_tpu.monitor.registry import MetricRegistry
from paddle_tpu.monitor.tracing import (TraceRetention, Tracer,
                                        set_default_tracer)
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine,
                                ServingGateway)
from paddle_tpu.serving.gateway import slo_burn_rate
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

MNT = 8


@pytest.fixture(scope='module')
def model():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def prompts():
    rng = np.random.RandomState(3)
    return [[int(t) for t in rng.randint(0, 211, n)]
            for n in (3, 17, 7, 12, 5, 21)]


def _ev(**kw):
    """A schema-complete event dict with overridable defaults."""
    base = dict(request_id='r', tenant='t', trace_id='tr', arrival_t=0.0,
                admit_t=0.1, first_token_t=0.2, finish_t=0.5,
                queue_wait_s=0.1, prefill_chunks=1, prompt_tokens=4,
                output_tokens=8, prefix_hit_tokens=0, spec_proposed=0,
                spec_accepted=0, kv_page_seconds=0.4, failovers=0,
                replicas=[0], outcome='ok')
    base.update(kw)
    return base


# ---- RequestLog -------------------------------------------------------


def test_emit_validates_schema_and_orders_fields():
    log = RequestLog(capacity=8, registry=MetricRegistry())
    ev = log.emit(**_ev(request_id='a'))
    assert tuple(ev.keys()) == FIELD_NAMES
    # a partial emit records None for missing fields, never KeyErrors
    ev2 = log.emit(request_id='b', outcome='error')
    assert ev2['tenant'] is None and ev2['kv_page_seconds'] is None
    with pytest.raises(ValueError, match='tennant'):
        log.emit(tennant='acme')
    assert len(log) == 2


def test_ring_bound_and_drop_counter():
    reg = MetricRegistry()
    log = RequestLog(capacity=3, registry=reg)
    for i in range(5):
        log.emit(**_ev(request_id='r%d' % i))
    assert len(log) == 3
    assert [e['request_id'] for e in log.events()] == ['r2', 'r3', 'r4']
    assert log.dropped == 2
    assert reg.get('request_events_total').value() == 5.0
    assert reg.get('request_events_dropped_total').value() == 2.0
    log.clear()
    assert len(log) == 0


def test_sink_writes_jsonl_and_rotates(tmp_path):
    reg = MetricRegistry()
    sink = str(tmp_path / 'req.jsonl')
    # ~350 bytes/line: a 1300-byte cap forces exactly one rotation
    # across 6 writes, so current + backup together hold every event
    log = RequestLog(capacity=64, sink_path=sink, max_sink_bytes=1300,
                     sink_backups=2, registry=reg)
    for i in range(6):
        log.emit(**_ev(request_id='r%d' % i))
    lines = [json.loads(ln) for ln in open(sink) if ln.strip()]
    assert lines and all(tuple(sorted(e)) == tuple(sorted(FIELD_NAMES))
                         for e in lines)
    assert reg.get('request_sink_rotations_total').value() == 1.0
    rotated = tmp_path / 'req.jsonl.1'
    assert rotated.exists()
    old = [json.loads(ln) for ln in open(str(rotated)) if ln.strip()]
    # nothing lost across the rotation boundary
    assert len(old) + len(lines) == 6


def test_event_filters():
    log = RequestLog(capacity=16, registry=MetricRegistry())
    log.emit(**_ev(request_id='a', tenant='p', outcome='ok', failovers=0))
    log.emit(**_ev(request_id='b', tenant='p', outcome='error',
                   failovers=2))
    log.emit(**_ev(request_id='c', tenant='q', outcome='ok', failovers=1))
    assert [e['request_id'] for e in log.events(tenant='p')] == ['a', 'b']
    assert [e['request_id'] for e in log.events(outcome='error')] == ['b']
    assert [e['request_id'] for e in log.events(min_failovers=1)] \
        == ['b', 'c']
    assert [e['request_id'] for e in log.events(limit=1)] == ['c']
    assert [e['request_id']
            for e in log.events(tenant='p', min_failovers=1, limit=5)] \
        == ['b']


def test_event_time_range_filters_are_half_open():
    log = RequestLog(capacity=16, registry=MetricRegistry())
    for i, t in enumerate((10.0, 20.0, 30.0)):
        log.emit(**_ev(request_id='t%d' % i, arrival_t=t))
    log.emit(**_ev(request_id='noarr', arrival_t=None))
    assert [e['request_id'] for e in log.events(since_ts=20.0)] \
        == ['t1', 't2']
    # [since, until): the until bound is exclusive
    assert [e['request_id'] for e in log.events(until_ts=20.0)] == ['t0']
    assert [e['request_id']
            for e in log.events(since_ts=10.0, until_ts=30.0)] \
        == ['t0', 't1']
    # string values coerce (the HTTP route's path), garbage raises
    assert [e['request_id'] for e in log.events(since_ts='25')] == ['t2']
    with pytest.raises(ValueError):
        log.events(since_ts='zap')
    # events that never entered the system carry no arrival_t and never
    # match a time window
    assert all(e['request_id'] != 'noarr'
               for e in log.events(since_ts=0.0))
    # composes with the other filters
    assert [e['request_id']
            for e in log.events(since_ts=10.0, limit=1)] == ['t2']


def test_concurrent_emit_is_safe():
    reg = MetricRegistry()
    log = RequestLog(capacity=4096, registry=reg)

    def writer(base):
        for i in range(200):
            log.emit(**_ev(request_id='%d-%d' % (base, i)))

    ts = [threading.Thread(target=writer, args=(b,)) for b in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not any(t.is_alive() for t in ts)
    assert len(log) == 800
    assert reg.get('request_events_total').value() == 800.0
    assert log.dropped == 0


def test_disabled_emit_is_cheap_and_inert():
    reg = MetricRegistry()
    log = RequestLog(capacity=8, registry=reg)
    log.disable()
    t0 = time.monotonic()
    for _ in range(100_000):
        assert log.emit(request_id='x') is None
    elapsed = time.monotonic() - t0
    # one attribute load + branch; the bound is deliberately loose for
    # CI jitter — the real budget is ~100ns/call
    assert elapsed < 2.0, elapsed
    assert len(log) == 0
    assert reg.get('request_events_total').value() == 0.0
    log.enable()
    assert log.emit(**_ev()) is not None


def test_tenant_labeler_bounds_cardinality():
    lab = TenantLabeler(cap=4, buckets=2)
    assert lab.label(None) == 'default'
    first = [lab.label('t%d' % i) for i in range(4)]
    assert first == ['t0', 't1', 't2', 't3']      # interned verbatim
    overflow = {lab.label('x%d' % i) for i in range(50)}
    assert overflow <= {'overflow_0', 'overflow_1'}
    # interned tenants keep their identity after overflow starts
    assert lab.label('t2') == 't2'
    # hashed bucket is stable per tenant
    assert lab.label('x7') == lab.label('x7')
    all_labels = set(first) | overflow | {'default'}
    assert len(all_labels) <= 4 + 2 + 1


def test_event_line_roundtrip():
    ev = _ev(request_id='rr', tenant='acme')
    line = event_line(ev, 4, '[cfg]')
    assert line.startswith('request_event(4)[cfg]: {')
    parsed = parse_event_lines('noise\n%s\nmore noise\n' % line)
    assert len(parsed) == 1
    tag, got = parsed[0]
    assert tag == 'cfg' and got == ev
    assert parse_event_lines('request_event(1)[x]: not json') == []


def test_default_log_swap_returns_previous():
    mine = RequestLog(capacity=4, registry=MetricRegistry())
    prev = set_default_request_log(mine)
    try:
        from paddle_tpu.monitor.events import default_request_log
        assert default_request_log() is mine
    finally:
        assert set_default_request_log(prev) is mine


# ---- TraceRetention ---------------------------------------------------


def _span(tid, name='root', parent=None, start=0.0, end=1.0,
          status='ok'):
    return {'trace_id': tid, 'span_id': name, 'parent_id': parent,
            'name': name, 'start': start, 'end': end, 'status': status}


def test_retention_keeps_slow_error_forced_and_samples():
    reg = MetricRegistry()
    ret = TraceRetention(capacity=16, slow_threshold_s=0.5,
                         keep_probability=0.0, registry=reg)
    # healthy + fast -> discarded
    ret.offer(_span('fast', end=0.1))
    assert ret.get('fast') is None
    assert reg.get('trace_retention_discarded_total').value() == 1.0
    # slow root -> kept with reason 'slow'
    ret.offer(_span('slow', end=2.0))
    assert [t['reasons'] for t in ret.traces(reason='slow')] == [['slow']]
    # an errored child keeps the whole tree
    ret.offer(_span('err', name='child', parent='root-id', status='error',
                    end=0.1))
    ret.offer(_span('err', end=0.1))
    tree = ret.get('err')
    assert tree is not None and len(tree) == 2
    # forced mark lands when the tree completes
    ret.mark('forced-tid', 'failover')
    ret.offer(_span('forced-tid', end=0.1))
    assert ret.traces(reason='failover')[0]['trace_id'] == 'forced-tid'
    assert reg.get('trace_retained_total').labels('failover').value() \
        == 1.0
    # probabilistic baseline keep with a deterministic rng
    ret2 = TraceRetention(capacity=4, keep_probability=0.5,
                          registry=MetricRegistry(), rng=lambda: 0.1)
    ret2.offer(_span('lucky', end=0.1))
    assert ret2.traces()[0]['reasons'] == ['sampled']


def test_retention_bounds_and_stragglers():
    reg = MetricRegistry()
    ret = TraceRetention(capacity=2, slow_threshold_s=0.0,
                         pending_capacity=2, registry=reg)
    for i in range(3):                       # every root is 'slow'
        ret.offer(_span('t%d' % i, end=1.0))
    assert len(ret) == 2                     # FIFO eviction at capacity
    assert ret.get('t0') is None and ret.get('t2') is not None
    assert reg.get('trace_retention_evicted_total').value() >= 1.0
    # pending (incomplete) trees are bounded too
    for i in range(4):
        ret.offer(_span('p%d' % i, name='c', parent='x', end=1.0))
    assert len(ret._pending) <= 2
    # straggler span of an already-kept tree is appended, not re-decided
    ret.offer(_span('t2', name='late-child', parent='root', end=1.5))
    names = [s['name'] for s in ret.get('t2')]
    assert 'late-child' in names
    ret.clear()
    assert len(ret) == 0


# ---- slo_burn_rate deque race (regression) ----------------------------


def test_slo_burn_rate_safe_under_concurrent_mutation():
    """Regression: slo_burn_rate used to iterate the gateway's _ttfts
    deque directly; a driver thread appending (and the maxlen evicting)
    mid-iteration raised ``RuntimeError: deque mutated during
    iteration``. The snapshot fix must survive a hostile writer."""
    samples = collections.deque(maxlen=512)
    stop = threading.Event()
    errors = []

    def writer():
        t = 0.0
        while not stop.is_set():
            t += 0.001
            samples.append((t, 0.9))

    th = threading.Thread(target=writer)
    th.start()
    try:
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            try:
                rate = slo_burn_rate(samples, time.monotonic(), 0.5, 30.0)
            except RuntimeError as e:          # pragma: no cover
                errors.append(e)
                break
            assert 0.0 <= rate <= 1.0
    finally:
        stop.set()
        th.join(10)
    assert not errors, errors


# ---- engine-level: one event per request + exact KV attribution -------


def test_slot_engine_one_event_per_request_kv_exact(model, prompts):
    log = RequestLog(capacity=64, registry=MetricRegistry())
    prev = set_default_request_log(log)
    try:
        eng = ContinuousBatchingEngine(model, num_slots=2, max_len=32,
                                       prefill_chunk=8, decode_block=2)
        # ServingMetrics rides the process default registry: assert
        # per-tenant deltas, not absolutes
        treg = eng.metrics.registry
        base_req = treg.get('tenant_requests_total') \
            .labels('premium').value()
        base_tok = treg.get('tenant_tokens_total').labels('batch').value()
        reqs = [eng.add_request(p, max_new_tokens=MNT,
                                tenant='premium' if i % 2 == 0 else
                                'batch')
                for i, p in enumerate(prompts)]
        eng.run()
    finally:
        set_default_request_log(prev)
    events = log.events()
    assert len(events) == len(prompts)              # exactly one each
    assert len({e['request_id'] for e in events}) == len(prompts)
    by_tenant = {}
    for e in events:
        by_tenant.setdefault(e['tenant'], []).append(e)
    assert sorted(by_tenant) == ['batch', 'premium']
    for e in events:
        assert e['outcome'] == 'ok' and e['failovers'] == 0
        assert e['output_tokens'] == MNT
        assert e['prompt_tokens'] in {len(p) for p in prompts}
        assert e['admit_t'] >= e['arrival_t']
        assert e['finish_t'] >= e['first_token_t'] >= e['admit_t']
        assert e['queue_wait_s'] == pytest.approx(
            e['admit_t'] - e['arrival_t'])
        assert e['kv_page_seconds'] > 0.0
    # THE attribution invariant: per-request slot·seconds sum EXACTLY
    # to the allocator's pool-occupancy integral (same clock reads)
    total = sum(e['kv_page_seconds'] for e in events)
    assert total == eng.allocator.page_seconds()
    assert sum(r.kv_page_seconds for r in reqs) == total
    # per-tenant families materialized with bounded labels
    assert treg.get('tenant_requests_total').labels('premium').value() \
        - base_req == 3.0
    assert treg.get('tenant_tokens_total').labels('batch').value() \
        - base_tok == 3.0 * MNT


def test_paged_engine_emits_spec_counts(model, prompts):
    log = RequestLog(capacity=64, registry=MetricRegistry())
    prev = set_default_request_log(log)
    try:
        eng = PagedContinuousBatchingEngine(
            model, num_seqs=2, max_len=32, page_size=8, prefill_chunk=8,
            decode_block=2, spec_k=2)
        eng.generate(prompts[:3], max_new_tokens=MNT, tenant='spec')
    finally:
        set_default_request_log(prev)
    events = log.events(tenant='spec')
    assert len(events) == 3
    assert all(e['kv_page_seconds'] > 0.0 for e in events)
    # the n-gram proposer drafted every decode step after the first
    assert sum(e['spec_proposed'] for e in events) > 0
    assert all(0 <= e['spec_accepted'] <= e['spec_proposed']
               for e in events)


def test_emit_event_false_suppresses_engine_event(model, prompts):
    """The gateway's replica path: the engine-level event is suppressed
    so the gateway emits the single canonical one."""
    log = RequestLog(capacity=16, registry=MetricRegistry())
    prev = set_default_request_log(log)
    try:
        eng = ContinuousBatchingEngine(model, num_slots=2, max_len=32,
                                       prefill_chunk=8, decode_block=2)
        eng.add_request(prompts[0], max_new_tokens=MNT, emit_event=False)
        eng.run()
    finally:
        set_default_request_log(prev)
    assert len(log) == 0


# ---- gateway chaos oracle ---------------------------------------------


@pytest.mark.chaos
def test_gateway_failover_chaos_oracle(model, prompts):
    """N failovers => exactly one wide event per submitted request, the
    victims carrying failovers=1 and both replicas in placement order,
    and exactly N failover-retained span trees retrievable by the wide
    events' trace_ids."""
    reg = MetricRegistry()
    log = RequestLog(capacity=64, registry=reg)
    ret = TraceRetention(capacity=64, registry=reg)
    tracer = Tracer(enabled=True, registry=reg, retention=ret)
    prev_log = set_default_request_log(log)
    prev_tr = set_default_tracer(tracer)
    try:
        gw = ServingGateway(
            lambda: ContinuousBatchingEngine(
                model, num_slots=2, max_len=32, prefill_chunk=8,
                decode_block=2),
            replicas=2, registry=reg)
        reqs = [gw.submit(p, max_new_tokens=MNT,
                          tenant='premium' if i % 2 == 0 else 'batch')
                for i, p in enumerate(prompts)]
        gw.step()
        gw.step()
        # the oracle: replica 0's in-flight non-finished requests at the
        # moment of loss — each fails over exactly once
        victims = [g for g in gw.pool[0].assigned if len(g.tokens) < MNT]
        expected = len(victims)
        assert expected > 0
        gw.kill_replica(0)
        gw.run()
    finally:
        set_default_request_log(prev_log)
        set_default_tracer(prev_tr)

    assert all(r.done for r in reqs)
    events = log.events()
    assert len(events) == len(prompts)              # EXACTLY one each
    assert len({e['request_id'] for e in events}) == len(prompts)
    failed_over = [e for e in events if e['failovers']]
    assert len(failed_over) == expected
    assert all(e['failovers'] == 1 for e in failed_over)
    assert all(e['replicas'] == [0, 1] for e in failed_over)
    assert reg.get('gateway_failover_total').value() == expected
    # tail retention kept EXACTLY the failed-over trees...
    kept = ret.traces(reason='failover')
    assert len(kept) == expected
    assert {t['trace_id'] for t in kept} \
        == {e['trace_id'] for e in failed_over}
    # ...and each wide event's trace_id joins to a full span tree
    for e in failed_over:
        tree = ret.get(e['trace_id'])
        assert tree is not None
        assert 'serving.request' in {s['name'] for s in tree}
    # untouched requests were not retained (no slow/sample reasons set)
    for e in events:
        if not e['failovers']:
            assert ret.get(e['trace_id']) is None
    # per-tenant counters on the gateway registry
    got = sum(reg.get('tenant_requests_total').labels(t).value()
              for t in ('premium', 'batch'))
    assert got == len(prompts)


# ---- /requests route --------------------------------------------------


def test_requests_route_serves_and_filters():
    log = RequestLog(capacity=16, registry=MetricRegistry())
    log.emit(**_ev(request_id='a', tenant='p', failovers=0))
    log.emit(**_ev(request_id='b', tenant='p', failovers=2,
                   outcome='error'))
    log.emit(**_ev(request_id='c', tenant='q', failovers=1))
    with MetricsServer(registry=MetricRegistry(), events=log) as srv:
        def get(qs=''):
            body = urllib.request.urlopen(
                srv.url + '/requests' + qs, timeout=5).read().decode()
            return json.loads(body)
        all_ev = get()
        assert all_ev['count'] == 3 and all_ev['dropped'] == 0
        assert [e['request_id'] for e in all_ev['events']] \
            == ['a', 'b', 'c']
        assert get('?tenant=p')['count'] == 2
        assert get('?outcome=error&tenant=p')['count'] == 1
        got = get('?min_failovers=1&limit=1')
        assert [e['request_id'] for e in got['events']] == ['c']
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + '/requests?limit=zap',
                                   timeout=5)
        assert ei.value.code == 400
    # a server with no log attached answers 404, like other optional
    # routes
    with MetricsServer(registry=MetricRegistry()) as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + '/requests', timeout=5)
        assert ei.value.code == 404


def test_requests_route_time_range_filters():
    log = RequestLog(capacity=16, registry=MetricRegistry())
    for i, t in enumerate((10.0, 20.0, 30.0)):
        log.emit(**_ev(request_id='t%d' % i, arrival_t=t))
    with MetricsServer(registry=MetricRegistry(), events=log) as srv:
        def get(qs=''):
            body = urllib.request.urlopen(
                srv.url + '/requests' + qs, timeout=5).read().decode()
            return json.loads(body)
        assert [e['request_id'] for e in get('?since_ts=20')['events']] \
            == ['t1', 't2']
        assert [e['request_id'] for e in get('?until_ts=20')['events']] \
            == ['t0']
        got = get('?since_ts=10&until_ts=30')
        assert [e['request_id'] for e in got['events']] == ['t0', 't1']
        assert get('?since_ts=20.5&tenant=t')['count'] == 1
        for bad in ('?since_ts=zap', '?until_ts=1e'):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + '/requests' + bad,
                                       timeout=5)
            assert ei.value.code == 400
