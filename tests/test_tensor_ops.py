"""Tensor op library tests (reference pattern: unittests/test_*_op.py via the
OpTest harness)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import OpTest


class TestMatmul(OpTest):
    fn = staticmethod(paddle.matmul)
    ref = staticmethod(np.matmul)

    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.inputs = {'x': rng.rand(4, 5).astype(np.float32),
                       'y': rng.rand(5, 3).astype(np.float32)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestAdd(OpTest):
    fn = staticmethod(paddle.add)
    ref = staticmethod(np.add)

    def setup_method(self, _):
        rng = np.random.RandomState(1)
        self.inputs = {'x': rng.rand(3, 4).astype(np.float32),
                       'y': rng.rand(3, 4).astype(np.float32)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestExp(OpTest):
    fn = staticmethod(paddle.exp)
    ref = staticmethod(np.exp)

    def setup_method(self, _):
        self.inputs = {'x': np.random.RandomState(2).rand(3, 4).astype(np.float32)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestSoftmax(OpTest):
    from paddle_tpu.nn.functional import softmax
    fn = staticmethod(softmax)

    @staticmethod
    def ref(x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def setup_method(self, _):
        self.inputs = {'x': np.random.RandomState(3).rand(5, 7).astype(np.float32)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestReduceSum(OpTest):
    fn = staticmethod(lambda x, axis=None: paddle.sum(x, axis=axis))
    ref = staticmethod(lambda x, axis=None: np.sum(x, axis=axis))

    def setup_method(self, _):
        self.inputs = {'x': np.random.RandomState(4).rand(3, 4, 5).astype(np.float32)}
        self.attrs = {'axis': 1}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


def test_creation_and_shape():
    x = paddle.zeros([3, 4])
    assert x.shape == [3, 4]
    assert x.dtype == 'float32'
    y = paddle.ones([2], dtype='int64')
    # int64 is stored as int32 on TPU unless x64 is enabled (documented
    # contract, framework/dtype.py)
    assert y.dtype in ('int64', 'int32')
    z = paddle.arange(10)
    assert z.shape == [10]
    e = paddle.eye(3)
    np.testing.assert_allclose(e.numpy(), np.eye(3))
    f = paddle.full([2, 2], 7.0)
    assert float(f.numpy()[0, 0]) == 7.0
    lin = paddle.linspace(0, 1, 5)
    np.testing.assert_allclose(lin.numpy(), np.linspace(0, 1, 5), rtol=1e-6)


def test_manipulation():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert paddle.reshape(x, [6, 4]).shape == [6, 4]
    assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(x, 1).shape == [2, 12]
    parts = paddle.split(x, 2, axis=2)
    assert len(parts) == 2 and parts[0].shape == [2, 3, 2]
    cat = paddle.concat([x, x], axis=0)
    assert cat.shape == [4, 3, 4]
    st = paddle.stack([x, x], axis=0)
    assert st.shape == [2, 2, 3, 4]
    sq = paddle.unsqueeze(x, [0])
    assert sq.shape == [1, 2, 3, 4]
    assert paddle.squeeze(sq, 0).shape == [2, 3, 4]
    t = paddle.tile(paddle.to_tensor([1., 2.]), [2, 3])
    assert t.shape == [2, 6]
    g = paddle.gather(paddle.to_tensor(np.arange(10.)), paddle.to_tensor([1, 3]))
    np.testing.assert_allclose(g.numpy(), [1., 3.])


def test_indexing_and_setitem():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4),
                         stop_gradient=False)
    y = x[1]
    assert y.shape == [4]
    z = x[:, 1:3]
    assert z.shape == [3, 2]
    # differentiable getitem
    s = z.sum()
    s.backward()
    expected = np.zeros((3, 4), np.float32)
    expected[:, 1:3] = 1
    np.testing.assert_allclose(x.grad.numpy(), expected)
    # setitem
    w = paddle.zeros([4])
    w[1] = 5.0
    np.testing.assert_allclose(w.numpy(), [0, 5, 0, 0])


def test_search_sort():
    x = paddle.to_tensor(np.asarray([[3., 1., 2.], [9., 7., 8.]], np.float32))
    vals, idx = paddle.topk(x, 2)
    np.testing.assert_allclose(vals.numpy(), [[3., 2.], [9., 8.]])
    am = paddle.argmax(x, axis=1)
    np.testing.assert_allclose(am.numpy(), [0, 0])
    s = paddle.sort(x, axis=1)
    np.testing.assert_allclose(s.numpy(), [[1., 2., 3.], [7., 8., 9.]])
    w = paddle.where(x > 2.5, x, paddle.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [[3., 0., 0.], [9., 7., 8.]])


def test_topk_grad_flows():
    x = paddle.to_tensor(np.asarray([[3., 1., 2.]], np.float32),
                         stop_gradient=False)
    vals, idx = paddle.topk(x, 2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1., 0., 1.]])


def test_linalg():
    rng = np.random.RandomState(0)
    a = rng.rand(4, 4).astype(np.float32) + np.eye(4, dtype=np.float32) * 4
    x = paddle.to_tensor(a)
    inv = paddle.inv(x)
    np.testing.assert_allclose(inv.numpy(), np.linalg.inv(a), atol=1e-4)
    det = paddle.det(x)
    np.testing.assert_allclose(det.numpy(), np.linalg.det(a), rtol=1e-4)
    n = paddle.norm(x)
    np.testing.assert_allclose(n.numpy(), np.linalg.norm(a), rtol=1e-5)
    sym = a @ a.T
    w = paddle.eigvalsh(paddle.to_tensor(sym))
    np.testing.assert_allclose(np.sort(w.numpy()),
                               np.sort(np.linalg.eigvalsh(sym)), rtol=1e-3)


def test_logic():
    x = paddle.to_tensor([1., 2., 3.])
    y = paddle.to_tensor([1., 5., 3.])
    np.testing.assert_array_equal((x == y).numpy(), [True, False, True])
    assert bool(paddle.allclose(x, x))
    assert not bool(paddle.equal_all(x, y))


def test_einsum():
    rng = np.random.RandomState(0)
    a = rng.rand(3, 4).astype(np.float32)
    b = rng.rand(4, 5).astype(np.float32)
    out = paddle.einsum('ij,jk->ik', paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_random_reproducible():
    paddle.seed(7)
    a = paddle.randn([4, 4]).numpy()
    paddle.seed(7)
    b = paddle.randn([4, 4]).numpy()
    np.testing.assert_allclose(a, b)


def test_operator_overloads():
    x = paddle.to_tensor([2., 4.])
    np.testing.assert_allclose((x + 1).numpy(), [3., 5.])
    np.testing.assert_allclose((1 - x).numpy(), [-1., -3.])
    np.testing.assert_allclose((x * x).numpy(), [4., 16.])
    np.testing.assert_allclose((x / 2).numpy(), [1., 2.])
    np.testing.assert_allclose((x ** 2).numpy(), [4., 16.])
    np.testing.assert_allclose((-x).numpy(), [-2., -4.])
    assert (x @ x).numpy() == pytest.approx(20.)


def test_cumsum_clip_cast():
    x = paddle.to_tensor([[1., 2.], [3., 4.]])
    np.testing.assert_allclose(paddle.cumsum(x, axis=0).numpy(),
                               [[1., 2.], [4., 6.]])
    np.testing.assert_allclose(paddle.clip(x, 1.5, 3.5).numpy(),
                               [[1.5, 2.], [3., 3.5]])
    assert paddle.cast(x, 'int32').dtype == 'int32'


def test_add_n_inverse_t_shard_index():
    a = paddle.to_tensor(np.eye(3, dtype=np.float32) * 4)
    np.testing.assert_allclose(paddle.inverse(a).numpy(),
                               np.linalg.inv(np.asarray(a.numpy())))
    m = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(paddle.t(m).numpy(), m.numpy().T)
    v = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_allclose(paddle.t(v).numpy(), [1.0, 2.0])

    s = paddle.add_n([m, m, m])
    np.testing.assert_allclose(s.numpy(), 3 * m.numpy())
    # add_n gradient flows to every addend
    x = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    paddle.add_n([x, y]).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones((2, 2)))
    np.testing.assert_allclose(y.grad.numpy(), np.ones((2, 2)))

    ids = paddle.to_tensor(np.asarray([0, 7, 8, 15], np.int64))
    out = paddle.shard_index(ids, index_num=16, nshards=2, shard_id=0)
    np.testing.assert_array_equal(out.numpy(), [0, 7, -1, -1])
    out1 = paddle.shard_index(ids, index_num=16, nshards=2, shard_id=1)
    np.testing.assert_array_equal(out1.numpy(), [-1, -1, 0, 7])


def test_check_numerics_and_profiler_utils(tmp_path):
    import pytest
    from paddle_tpu.framework.debug import check_numerics
    check_numerics(paddle.to_tensor([1.0]), 'x')
    with pytest.raises(FloatingPointError, match='1 NaN'):
        check_numerics(paddle.to_tensor([float('nan')]), 'x')
    from paddle_tpu import profiler
    with profiler.RecordEvent('unit_test_span'):
        pass
    assert profiler.load_profiler_result(str(tmp_path)) == []
