"""Blockwise (pure-XLA online-softmax) attention + multi-step device loop.

Parity bars: blockwise must match the quadratic reference numerically
(fwd AND grads — same contract tests/test_flash_attention.py holds the
Pallas kernels to), and TrainStep.multi_step(K) must reproduce K
sequential TrainStep() calls bit-for-bit-in-f32.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.blockwise_attention import (blockwise_attention,
                                                blockwise_attention_bnhd)


def _ref_bnhd(q, k, v, causal, scale):
    s = jnp.einsum('bhqd,bhkd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        # bottom-right aligned (decode-correct; flash-attn convention)
        n, m = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((n, m), bool), m - n), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bhkd->bhqd', p,
                      v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('n,block', [(256, 64), (384, 128), (512, 512)])
def test_blockwise_matches_reference_fwd(causal, n, block):
    rng = np.random.RandomState(0)
    b, h, d = 2, 3, 32
    q = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    out = blockwise_attention_bnhd(q, k, v, causal=causal, scale=scale,
                                   block_q=block, block_k=block)
    ref = _ref_bnhd(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_blockwise_matches_reference_grads(causal):
    rng = np.random.RandomState(1)
    b, h, n, d = 1, 2, 256, 16
    q = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def loss_bw(q, k, v):
        return jnp.sum(blockwise_attention_bnhd(
            q, k, v, causal=causal, scale=scale, block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_bnhd(q, k, v, causal, scale) ** 2)

    g_bw = jax.grad(loss_bw, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_bw, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_blockwise_paddle_layout_and_uneven_blocks():
    # paddle [B, N, H, D] layout entry; n not divisible by the default
    # block target exercises _pick_block's divisor shrink
    rng = np.random.RandomState(2)
    b, n, h, d = 2, 320, 2, 16
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    out = blockwise_attention(q, k, v, causal=True)
    ref = jnp.swapaxes(_ref_bnhd(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                                 jnp.swapaxes(v, 1, 2), True,
                                 1.0 / np.sqrt(d)), 1, 2)
    assert out.shape == (b, n, h, d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_attn_impl_env_routes_blockwise(monkeypatch):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    monkeypatch.setenv('PADDLE_TPU_ATTN_IMPL', 'blockwise')
    rng = np.random.RandomState(3)
    q = paddle.to_tensor(rng.randn(2, 128, 2, 16).astype(np.float32))
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    monkeypatch.setenv('PADDLE_TPU_ATTN_IMPL', 'quadratic')
    ref = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-5,
                               atol=2e-5)


@pytest.mark.slow
def test_multi_step_under_dp_sharding():
    """multi_step under a fleet dp strategy: the K-leading stacked batch
    must shard its BATCH dim (dim 1) over dp, not the scan axis — and
    match the sequential per-step losses."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet

    def build():
        paddle.seed(9)
        from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=16,
                        dropout=0.0)
        model = GPTForCausalLM(cfg)
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {'dp_degree': 4, 'mp_degree': 1, 'pp_degree': 1,
                            'sharding_degree': 1, 'sp_degree': 1}
        fleet.init(is_collective=True, strategy=s)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        return fleet.fleet_train_step(
            model, lambda lg, lb: model.loss(lg, lb), opt, strategy=s)

    rng = np.random.RandomState(5)
    k = 3
    ids = rng.randint(0, 64, (k, 8, 16)).astype(np.int32)

    step_a = build()
    seq = [float(step_a(paddle.to_tensor(ids[i]),
                        paddle.to_tensor(ids[i])).numpy())
           for i in range(k)]
    step_b = build()
    multi = step_b.multi_step(paddle.to_tensor(ids),
                              paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(multi, seq, rtol=1e-4, atol=1e-5)


def test_multi_step_composes_with_gradient_merge():
    """K-step scan over a gradient-merge (k_steps=2) step: the merge's
    lax.cond carry (acc/micro counters) must thread the scan exactly as
    in sequential execution."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import TrainStep

    def build():
        paddle.seed(3)
        model = paddle.nn.Linear(6, 3)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        return model, TrainStep(
            model, lambda out, y: paddle.nn.functional.mse_loss(out, y),
            opt, k_steps=2)

    rng = np.random.RandomState(8)
    k = 4
    xs = rng.randn(k, 10, 6).astype(np.float32)
    ys = rng.randn(k, 10, 3).astype(np.float32)

    model_a, step_a = build()
    seq = [float(step_a(paddle.to_tensor(xs[i]),
                        paddle.to_tensor(ys[i])).numpy())
           for i in range(k)]
    model_b, step_b = build()
    multi = step_b.multi_step(paddle.to_tensor(xs),
                              paddle.to_tensor(ys)).numpy()
    np.testing.assert_allclose(multi, seq, rtol=1e-5, atol=1e-6)
    for (na, pa), (nb, pb) in zip(model_a.named_parameters(),
                                  model_b.named_parameters()):
        np.testing.assert_allclose(pa.numpy(), pb.numpy(), rtol=1e-5,
                                   atol=1e-6)


def test_multi_step_matches_sequential():
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import TrainStep

    def build():
        paddle.seed(7)
        model = paddle.nn.Sequential(
            paddle.nn.Linear(8, 32), paddle.nn.GELU(),
            paddle.nn.Linear(32, 4))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        return model, TrainStep(
            model, lambda out, y: paddle.nn.functional.mse_loss(out, y), opt)

    rng = np.random.RandomState(4)
    k = 5
    xs = rng.randn(k, 16, 8).astype(np.float32)
    ys = rng.randn(k, 16, 4).astype(np.float32)

    model_a, step_a = build()
    losses_seq = [float(step_a(paddle.to_tensor(xs[i]),
                               paddle.to_tensor(ys[i])).numpy())
                  for i in range(k)]

    model_b, step_b = build()
    losses_multi = step_b.multi_step(paddle.to_tensor(xs),
                                     paddle.to_tensor(ys)).numpy()

    assert losses_multi.shape == (k,)
    np.testing.assert_allclose(losses_multi, np.asarray(losses_seq),
                               rtol=1e-5, atol=1e-6)
    for (na, pa), (nb, pb) in zip(model_a.named_parameters(),
                                  model_b.named_parameters()):
        assert na == nb
        np.testing.assert_allclose(pa.numpy(), pb.numpy(), rtol=1e-5,
                                   atol=1e-6)


def _weighted_dot_flops(jaxpr, mult=1):
    """Matmul flops of a jaxpr with scan bodies weighted by trip count
    (XLA's cost_analysis counts a while-body once, hiding the real work)."""
    total = 0
    for eqn in jaxpr.eqns:
        m = mult
        sub = []
        if eqn.primitive.name == 'scan':
            m = mult * eqn.params['length']
            sub = [eqn.params['jaxpr'].jaxpr]
        else:
            for vparam in eqn.params.values():
                if hasattr(vparam, 'eqns'):
                    sub.append(vparam)
                elif hasattr(vparam, 'jaxpr') and \
                        hasattr(vparam.jaxpr, 'eqns'):
                    sub.append(vparam.jaxpr)
        if eqn.primitive.name == 'dot_general':
            lhs = eqn.invars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            (lc, rc), (lb, rb) = eqn.params['dimension_numbers']
            bsz = mdim = ndim = kdim = 1
            for a in lb:
                bsz *= lhs[a]
            for i, s in enumerate(lhs):
                if i not in lc and i not in lb:
                    mdim *= s
            for i, s in enumerate(rhs):
                if i not in rc and i not in rb:
                    ndim *= s
            for a in lc:
                kdim *= lhs[a]
            total += 2 * bsz * mdim * ndim * kdim * m
        for s in sub:
            total += _weighted_dot_flops(s, m)
    return total


def test_causal_skip_halves_flops():
    """The causal path must actually SKIP future kv blocks (static
    lower-triangle slices), not compute-then-mask: trip-count-weighted
    matmul flops must equal the lower-triangle fraction of the square."""
    b, h, n, d, blk = 1, 2, 512, 32, 64   # tq = 8

    def count(causal):
        def f(q, k, v):
            return blockwise_attention_bnhd(q, k, v, causal=causal,
                                            block_q=blk, block_k=blk)
        x = jnp.zeros((b, h, n, d), jnp.float32)
        return _weighted_dot_flops(jax.make_jaxpr(f)(x, x, x).jaxpr)

    full = count(False)
    tri = count(True)
    tq = n // blk
    assert tri == full * (tq + 1) // (2 * tq), (tri, full)


def test_causal_cross_attention_fallback():
    """causal with n != m (or unequal blocks) uses the masked fallback and
    stays correct."""
    rng = np.random.RandomState(7)
    b, h, d = 1, 2, 16
    n, m = 128, 256
    q = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, m, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, m, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    out = blockwise_attention_bnhd(q, k, v, causal=True, scale=scale,
                                   block_q=64, block_k=64)
    ref = _ref_bnhd(q, k, v, True, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kv_cache_decode_matches_full_forward():
    """Bottom-right causal alignment end-to-end: GPT incremental decode
    with a KV cache must reproduce the full forward's last position.
    Regression: the top-left tril masked the decode token down to key 0."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models.gpt import GPTConfig, GPTAttention

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=8, dropout=0.0)
    attn = GPTAttention(cfg)
    attn.eval()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(1, 4, 16).astype(np.float32))

    full = attn(x).numpy()
    out3, cache = attn(paddle.to_tensor(x.numpy()[:, :3]), cache=(
        paddle.zeros([1, 0, 2, 8]), paddle.zeros([1, 0, 2, 8])))
    np.testing.assert_allclose(out3.numpy()[0], full[0, :3], atol=1e-5)
    step4, cache = attn(paddle.to_tensor(x.numpy()[:, 3:4]), cache=cache)
    np.testing.assert_allclose(step4.numpy()[0, 0], full[0, 3], atol=1e-5)


def test_default_block_size_degrades_gracefully():
    """Without PADDLE_TPU_BLOCKWISE_BLOCK set, non-512-divisible lengths
    must flow through _pick_block's divisor shrink, not raise."""
    import os
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    assert 'PADDLE_TPU_BLOCKWISE_BLOCK' not in os.environ
    os.environ['PADDLE_TPU_ATTN_IMPL'] = 'blockwise'
    try:
        rng = np.random.RandomState(4)
        q = paddle.to_tensor(rng.randn(1, 640, 2, 16).astype(np.float32))
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        os.environ['PADDLE_TPU_ATTN_IMPL'] = 'quadratic'
        ref = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-5,
                                   atol=2e-5)
    finally:
        os.environ.pop('PADDLE_TPU_ATTN_IMPL', None)
