"""Optimizer tests (reference pattern: unittests/test_sgd_op.py,
test_adam_op.py ...: update rules vs numpy reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _quad_problem(opt_factory, steps=100):
    paddle.seed(0)
    target = np.asarray([1.0, -2.0, 3.0], np.float32)
    w = paddle.framework.Parameter(np.zeros(3, np.float32))
    opt = opt_factory([w])
    for _ in range(steps):
        loss = ((w - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy(), target


@pytest.mark.parametrize('factory', [
    lambda ps: paddle.optimizer.SGD(learning_rate=0.1, parameters=ps),
    lambda ps: paddle.optimizer.Momentum(learning_rate=0.05, parameters=ps),
    lambda ps: paddle.optimizer.Adam(learning_rate=0.2, parameters=ps),
    lambda ps: paddle.optimizer.AdamW(learning_rate=0.2, parameters=ps,
                                      weight_decay=0.0),
    lambda ps: paddle.optimizer.RMSProp(learning_rate=0.05, parameters=ps),
    lambda ps: paddle.optimizer.Adagrad(learning_rate=0.5, parameters=ps),
    lambda ps: paddle.optimizer.Adamax(learning_rate=0.2, parameters=ps),
    lambda ps: paddle.optimizer.Adadelta(learning_rate=10.0, parameters=ps),
    lambda ps: paddle.optimizer.Lamb(learning_rate=0.1, parameters=ps,
                                     lamb_weight_decay=0.0),
], ids=['sgd', 'momentum', 'adam', 'adamw', 'rmsprop', 'adagrad', 'adamax',
        'adadelta', 'lamb'])
def test_optimizers_converge(factory):
    w, target = _quad_problem(factory, steps=300)
    np.testing.assert_allclose(w, target, atol=0.3)


def test_sgd_exact_rule():
    w = paddle.framework.Parameter(np.asarray([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    (w * 3.0).backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 3.0], rtol=1e-6)


def test_adam_matches_reference_formula():
    rng = np.random.RandomState(0)
    w0 = rng.rand(4).astype(np.float32)
    g = rng.rand(4).astype(np.float32)
    w = paddle.framework.Parameter(w0.copy())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[w])
    (w * paddle.to_tensor(g)).sum().backward()
    opt.step()
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    ref = w0 - 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(w.numpy(), ref, rtol=1e-5)


def test_weight_decay_l2():
    w = paddle.framework.Parameter(np.asarray([2.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w],
                               weight_decay=0.5)
    (w * 0.0).sum().backward()
    opt.step()
    # grad = 0 + 0.5*2.0 = 1.0 -> w = 2 - 0.1
    np.testing.assert_allclose(w.numpy(), [1.9], rtol=1e-6)


def test_lr_scheduler_step():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                          gamma=0.5)
    w = paddle.framework.Parameter(np.zeros(1, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
    lrs = []
    for i in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)


def test_lr_schedulers_shapes():
    L = paddle.optimizer.lr
    scheds = [
        L.NoamDecay(128, 100), L.PiecewiseDecay([3, 6], [0.1, 0.05, 0.01]),
        L.NaturalExpDecay(0.1, 0.5), L.InverseTimeDecay(0.1, 0.5),
        L.PolynomialDecay(0.1, 10), L.ExponentialDecay(0.1, 0.9),
        L.MultiStepDecay(0.1, [2, 4]), L.StepDecay(0.1, 3),
        L.LambdaDecay(0.1, lambda e: 0.9 ** e),
        L.CosineAnnealingDecay(0.1, 10),
        L.LinearWarmup(0.1, 5, 0.0, 0.1),
        L.OneCycleLR(0.1, 20), L.CyclicLR(0.01, 0.1, 5),
    ]
    for s in scheds:
        for _ in range(8):
            s.step()
        assert np.isfinite(s())


def test_optimizer_state_dict_roundtrip():
    w = paddle.framework.Parameter(np.ones(3, np.float32))
    w.name = 'w'
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w ** 2).sum().backward()
    opt.step()
    state = opt.state_dict()
    assert state['step'] == 1

    w2 = paddle.framework.Parameter(np.ones(3, np.float32))
    w2.name = 'w'
    opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(state)
    assert opt2._step_count == 1
    np.testing.assert_allclose(opt2._get_slots(w2)['moment1'],
                               opt._get_slots(w)['moment1'])


def test_grad_scaler_fp16_contract():
    from paddle_tpu.amp import GradScaler
    w = paddle.framework.Parameter(np.ones(2, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = GradScaler(init_loss_scaling=4.0)
    loss = (w * 2).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    scaler.update()
    # unscaled grad = 2 -> w = 1 - 0.2
    np.testing.assert_allclose(w.numpy(), [0.8, 0.8], rtol=1e-6)
