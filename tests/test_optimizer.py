"""Optimizer tests (reference pattern: unittests/test_sgd_op.py,
test_adam_op.py ...: update rules vs numpy reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _quad_problem(opt_factory, steps=100):
    paddle.seed(0)
    target = np.asarray([1.0, -2.0, 3.0], np.float32)
    w = paddle.framework.Parameter(np.zeros(3, np.float32))
    opt = opt_factory([w])
    for _ in range(steps):
        loss = ((w - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy(), target


@pytest.mark.parametrize('factory', [
    lambda ps: paddle.optimizer.SGD(learning_rate=0.1, parameters=ps),
    lambda ps: paddle.optimizer.Momentum(learning_rate=0.05, parameters=ps),
    lambda ps: paddle.optimizer.Adam(learning_rate=0.2, parameters=ps),
    lambda ps: paddle.optimizer.AdamW(learning_rate=0.2, parameters=ps,
                                      weight_decay=0.0),
    lambda ps: paddle.optimizer.RMSProp(learning_rate=0.05, parameters=ps),
    lambda ps: paddle.optimizer.Adagrad(learning_rate=0.5, parameters=ps),
    lambda ps: paddle.optimizer.Adamax(learning_rate=0.2, parameters=ps),
    lambda ps: paddle.optimizer.Adadelta(learning_rate=10.0, parameters=ps),
    lambda ps: paddle.optimizer.Lamb(learning_rate=0.1, parameters=ps,
                                     lamb_weight_decay=0.0),
], ids=['sgd', 'momentum', 'adam', 'adamw', 'rmsprop', 'adagrad', 'adamax',
        'adadelta', 'lamb'])
def test_optimizers_converge(factory):
    w, target = _quad_problem(factory, steps=300)
    np.testing.assert_allclose(w, target, atol=0.3)


def test_sgd_exact_rule():
    w = paddle.framework.Parameter(np.asarray([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    (w * 3.0).backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 3.0], rtol=1e-6)


def test_adam_matches_reference_formula():
    rng = np.random.RandomState(0)
    w0 = rng.rand(4).astype(np.float32)
    g = rng.rand(4).astype(np.float32)
    w = paddle.framework.Parameter(w0.copy())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[w])
    (w * paddle.to_tensor(g)).sum().backward()
    opt.step()
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    ref = w0 - 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(w.numpy(), ref, rtol=1e-5)


def test_weight_decay_l2():
    w = paddle.framework.Parameter(np.asarray([2.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w],
                               weight_decay=0.5)
    (w * 0.0).sum().backward()
    opt.step()
    # grad = 0 + 0.5*2.0 = 1.0 -> w = 2 - 0.1
    np.testing.assert_allclose(w.numpy(), [1.9], rtol=1e-6)


def test_lr_scheduler_step():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                          gamma=0.5)
    w = paddle.framework.Parameter(np.zeros(1, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
    lrs = []
    for i in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)


def test_lr_schedulers_shapes():
    L = paddle.optimizer.lr
    scheds = [
        L.NoamDecay(128, 100), L.PiecewiseDecay([3, 6], [0.1, 0.05, 0.01]),
        L.NaturalExpDecay(0.1, 0.5), L.InverseTimeDecay(0.1, 0.5),
        L.PolynomialDecay(0.1, 10), L.ExponentialDecay(0.1, 0.9),
        L.MultiStepDecay(0.1, [2, 4]), L.StepDecay(0.1, 3),
        L.LambdaDecay(0.1, lambda e: 0.9 ** e),
        L.CosineAnnealingDecay(0.1, 10),
        L.LinearWarmup(0.1, 5, 0.0, 0.1),
        L.OneCycleLR(0.1, 20), L.CyclicLR(0.01, 0.1, 5),
    ]
    for s in scheds:
        for _ in range(8):
            s.step()
        assert np.isfinite(s())


def test_optimizer_state_dict_roundtrip():
    w = paddle.framework.Parameter(np.ones(3, np.float32))
    w.name = 'w'
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w ** 2).sum().backward()
    opt.step()
    state = opt.state_dict()
    assert state['step'] == 1

    w2 = paddle.framework.Parameter(np.ones(3, np.float32))
    w2.name = 'w'
    opt2 = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(state)
    assert opt2._step_count == 1
    np.testing.assert_allclose(opt2._get_slots(w2)['moment1'],
                               opt._get_slots(w)['moment1'])


def test_grad_scaler_fp16_contract():
    from paddle_tpu.amp import GradScaler
    w = paddle.framework.Parameter(np.ones(2, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = GradScaler(init_loss_scaling=4.0)
    loss = (w * 2).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    scaler.update()
    # unscaled grad = 2 -> w = 1 - 0.2
    np.testing.assert_allclose(w.numpy(), [0.8, 0.8], rtol=1e-6)


def test_multi_step_bf16_params_keep_dtype():
    """A bf16 model's params/slots must not drift to f32 through the jitted
    update (the traced f32 lr promotes the update arithmetic — good — but
    the stored dtypes must round-trip or the lax.scan carry in multi_step
    mistypes). Regression: the bench's bf16 TPU rung failed exactly here."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import TrainStep

    paddle.seed(11)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.GELU(), paddle.nn.Linear(16, 4))
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01,
                                 parameters=model.parameters())

    def loss_fn(out, lab):
        return paddle.nn.functional.cross_entropy(out.astype('float32'), lab)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(5)
    x = paddle.to_tensor(
        rng.randn(4, 6, 8).astype(np.float32)).astype('bfloat16')
    y = paddle.to_tensor(rng.randint(0, 4, (4, 6)).astype(np.int64))

    # single step: params stay bf16 (no silent f32 upcast + recompile)
    step(x, y)
    for p in model.parameters():
        assert p.dtype == paddle.bfloat16, p.name

    # multi_step: the scan carry must type-check, losses finite
    k = 3
    xk = paddle.to_tensor(np.broadcast_to(x.numpy(), (k, 4, 6, 8)).copy())
    yk = paddle.to_tensor(np.broadcast_to(y.numpy(), (k, 4, 6)).copy())
    losses = step.multi_step(xk, yk).numpy()
    assert losses.shape == (k,)
    assert np.isfinite(losses.astype(np.float32)).all()
    for p in model.parameters():
        assert p.dtype == paddle.bfloat16, p.name


def test_bf16_optimizer_state_is_f32():
    """Low-precision params get f32 optimizer state (bf16 moments freeze:
    (1-b2)*g^2 is below bf16 resolution at beta2=0.999), and
    multi_precision=True additionally keeps an f32 master param."""
    import jax.numpy as jnp
    import paddle_tpu as paddle

    paddle.seed(3)
    lin = paddle.nn.Linear(4, 4)
    lin.bfloat16()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=lin.parameters())
    p = lin.parameters()[0]
    slots = opt._get_slots(p)
    assert slots['moment1'].dtype == jnp.float32
    assert slots['moment2'].dtype == jnp.float32
    assert 'master' not in slots

    # EMA actually accumulates: with bf16 moments this stalls at 0
    x = paddle.to_tensor(np.ones((2, 4), np.float32)).astype('bfloat16')
    for _ in range(3):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    slots = opt._get_slots(p)
    assert float(jnp.abs(slots['moment2']).max()) > 0
    assert p.dtype == paddle.bfloat16

    paddle.seed(3)
    lin2 = paddle.nn.Linear(4, 4)
    lin2.bfloat16()
    opt2 = paddle.optimizer.AdamW(learning_rate=1e-3, multi_precision=True,
                                  parameters=lin2.parameters())
    p2 = lin2.parameters()[0]
    slots2 = opt2._get_slots(p2)
    assert slots2['master'].dtype == jnp.float32
    loss = (lin2(x) ** 2).mean()
    loss.backward()
    opt2.step()
    opt2.clear_grad()
    slots2 = opt2._get_slots(p2)
    # stored param is the rounded shadow of the updated master
    np.testing.assert_array_equal(
        np.asarray(slots2['master'].astype(jnp.bfloat16), np.float32),
        p2.numpy().astype(np.float32))


def test_multi_precision_multi_step():
    """multi_precision master weights ride through the jitted multi_step
    scan: master persists f32 in the opt-state carry, stored params stay
    bf16, and tiny updates that round to zero in bf16 still accumulate
    in the master."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import TrainStep

    paddle.seed(13)
    model = paddle.nn.Linear(8, 4)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-7, multi_precision=True,
                                 parameters=model.parameters())
    step = TrainStep(model, lambda out, y:
                     paddle.nn.functional.mse_loss(
                         out.astype('float32'), y), opt)
    rng = np.random.RandomState(2)
    k = 4
    xs = paddle.to_tensor(
        rng.randn(k, 8, 8).astype(np.float32)).astype('bfloat16')
    ys = paddle.to_tensor(rng.randn(k, 8, 4).astype(np.float32))
    m0 = np.asarray(opt._get_slots(model.parameters()[0])['master'],
                    np.float32).copy()
    losses = step.multi_step(xs, ys).numpy()
    assert losses.shape == (k,)
    p0 = model.parameters()[0]
    assert p0.dtype == paddle.bfloat16
    m1 = opt._get_slots(p0)['master']
    assert m1.dtype == jnp.float32
    # lr=1e-7 moves the master below bf16 resolution: the shadow may not
    # change, the master must
    assert np.abs(np.asarray(m1, np.float32) - m0).max() > 0


def test_grad_merge_bf16_acc_is_f32():
    """Gradient-merge accumulators for bf16 params are f32 (summing K
    same-magnitude grads in bf16 loses ~log2(K) mantissa bits), and the
    k_steps path stays scan-carry-type-stable for bf16 models."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import TrainStep

    paddle.seed(17)
    model = paddle.nn.Linear(6, 3)
    model.bfloat16()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = TrainStep(model, lambda out, y:
                     paddle.nn.functional.mse_loss(
                         out.astype('float32'), y), opt, k_steps=2)
    acc = step._opt_state()['acc']
    assert all(a.dtype == jnp.float32 for a in acc.values())

    rng = np.random.RandomState(3)
    k = 4
    xs = paddle.to_tensor(
        rng.randn(k, 5, 6).astype(np.float32)).astype('bfloat16')
    ys = paddle.to_tensor(rng.randn(k, 5, 3).astype(np.float32))
    losses = step.multi_step(xs, ys).numpy()
    assert losses.shape == (k,)
    assert np.isfinite(losses.astype(np.float32)).all()
    for p in model.parameters():
        assert p.dtype == paddle.bfloat16


def test_bf16_step_compiles_once():
    """The jitted step must not retrace after the first bf16 step: the
    old dtype drift silently recompiled to an f32 program on step 2 (the
    f32-matmul slowdown behind the r3/r4 197-198 ms/step TPU plateau)."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.functional import TrainStep

    paddle.seed(29)
    m = paddle.nn.Linear(8, 4)
    m.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    step = TrainStep(m, lambda o, y: paddle.nn.functional.mse_loss(
        o.astype('float32'), y), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(
        rng.randn(4, 8).astype(np.float32)).astype('bfloat16')
    y = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
    for _ in range(3):
        step(x, y)
    assert step._jitted._cache_size() == 1
