"""Test bootstrap: force an 8-device virtual CPU mesh (SURVEY.md §4.2-d).

Tests never want the single real TPU behind the axon tunnel — they want 8
virtual CPU devices so sharding/mesh tests run hardware-free (the
reference's analog is TestDistBase spawning localhost trainers). Backend
selection is lazy in jax, so flipping config here (before any test touches
a backend) is sufficient; XLA_FLAGS is read when the CPU client initializes.
"""
import os

os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                           ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'slow: scale/perf datapoints excluded from the tier-1 '
        "run (-m 'not slow')")
    config.addinivalue_line(
        'markers', 'chaos: fault-injection tests (testing/chaos.py) that '
        'exercise failure paths against live loopback servers')


@pytest.fixture
def seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    return 2024
