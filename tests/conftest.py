"""Test bootstrap: force an 8-device virtual CPU mesh (SURVEY.md §4.2-d).

Tests never want the single real TPU behind the axon tunnel — they want 8
virtual CPU devices so sharding/mesh tests run hardware-free (the
reference's analog is TestDistBase spawning localhost trainers). Backend
selection is lazy in jax, so flipping config here (before any test touches
a backend) is sufficient; XLA_FLAGS is read when the CPU client initializes.
"""
import os

os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                           ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'slow: scale/perf datapoints excluded from the tier-1 '
        "run (-m 'not slow')")
    config.addinivalue_line(
        'markers', 'chaos: fault-injection tests (testing/chaos.py) that '
        'exercise failure paths against live loopback servers')
    config.addinivalue_line(
        'markers', 'partial_auto: needs partial-auto shard_map (Manual '
        'over some mesh axes, Auto over the rest); skipped when the '
        'backend cannot SPMD-partition the PartitionId instruction the '
        'legacy lowering emits')


_PARTIAL_AUTO_OK = None


def _partial_auto_supported():
    """Capability probe, compiled once per session: the 0.4.x legacy
    shard_map partial-auto path (auto axes non-empty on a multi-real-axis
    mesh) lowers axis_index to an HLO PartitionId, which some backends
    (CPU jaxlib 0.4.37 among them) refuse to SPMD-partition. Probing the
    exact pattern keeps the pipeline tests honest: they run wherever the
    lowering works and skip (not error) where it cannot."""
    global _PARTIAL_AUTO_OK
    if _PARTIAL_AUTO_OK is None:
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec
        from paddle_tpu.distributed.shard_map_compat import shard_map
        try:
            mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                        ('pp', 'dp'))
            fn = shard_map(lambda x: x + jax.lax.axis_index('pp'),
                           mesh, in_specs=PartitionSpec('pp'),
                           out_specs=PartitionSpec('pp'),
                           axis_names=('pp',))
            jax.jit(fn)(jnp.zeros((2,), jnp.int32)).block_until_ready()
            _PARTIAL_AUTO_OK = True
        except Exception:
            _PARTIAL_AUTO_OK = False
    return _PARTIAL_AUTO_OK


def pytest_runtest_setup(item):
    if (item.get_closest_marker('partial_auto')
            and not _partial_auto_supported()):
        pytest.skip('backend cannot SPMD-partition PartitionId (legacy '
                    'partial-auto shard_map lowering)')


@pytest.fixture
def seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    return 2024
