"""Real-ONNX-export validation (VERDICT r2 missing #6): the exported
.onnx bytes are parsed back with a minimal protobuf reader and executed
with a numpy evaluator; outputs must match the eager forward.

This proves paddle.onnx.export emits a REAL self-contained ONNX graph
(nodes + initializers + typed IO), not a manifest."""
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static.input_spec import InputSpec


# -- minimal ONNX protobuf reader -------------------------------------------

def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf):
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError('wire %d' % wire)
        yield field, wire, val


_NP_OF_ONNX = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
               10: np.float16, 11: np.float64, 2: np.uint8, 3: np.int8}


def _parse_tensor(buf):
    dims, dtype, raw, name = [], 1, b'', ''
    for f, w, v in _fields(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    arr = np.frombuffer(raw, dtype=_NP_OF_ONNX[dtype]).reshape(dims).copy()
    return name, arr


def _parse_attr(buf):
    name, atype = '', None
    ival = fval = sval = None
    ints = []
    for f, w, v in _fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 20:
            atype = v
        elif f == 3:
            ival = v
        elif f == 2:
            fval = struct.unpack('<f', v)[0]
        elif f == 4:
            sval = v.decode()
        elif f == 8:
            # packed ints
            p = 0
            while p < len(v):
                x, p = _read_varint(v, p)
                if x >= 1 << 63:
                    x -= 1 << 64
                ints.append(x)
    if atype == 7:
        return name, ints
    if atype == 2:
        return name, ival
    if atype == 1:
        return name, fval
    if atype == 3:
        return name, sval
    return name, ints or ival or fval or sval


def _parse_node(buf):
    ins, outs, op, attrs = [], [], '', {}
    for f, w, v in _fields(buf):
        if f == 1:
            ins.append(v.decode())
        elif f == 2:
            outs.append(v.decode())
        elif f == 4:
            op = v.decode()
        elif f == 5:
            k, val = _parse_attr(v)
            attrs[k] = val
    return op, ins, outs, attrs


def _parse_model(blob):
    graph = None
    for f, w, v in _fields(blob):
        if f == 7:
            graph = v
    assert graph is not None, 'ModelProto.graph missing'
    nodes, inits, g_in, g_out = [], {}, [], []
    for f, w, v in _fields(graph):
        if f == 1:
            nodes.append(_parse_node(v))
        elif f == 5:
            name, arr = _parse_tensor(v)
            inits[name] = arr
        elif f == 11:
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    g_in.append(v2.decode())
        elif f == 12:
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    g_out.append(v2.decode())
    return nodes, inits, g_in, g_out


# -- numpy evaluator ---------------------------------------------------------

def _run_onnx(blob, feeds):
    nodes, inits, g_in, g_out = _parse_model(blob)
    env = dict(inits)
    env.update(feeds)

    def ev(op, ins, outs, attrs):
        a = [env[n] for n in ins]
        if op == 'MatMul':
            r = a[0] @ a[1]
        elif op == 'Add':
            r = a[0] + a[1]
        elif op == 'Sub':
            r = a[0] - a[1]
        elif op == 'Mul':
            r = a[0] * a[1]
        elif op == 'Div':
            r = a[0] / a[1]
        elif op == 'Max':
            r = np.maximum(a[0], a[1])
        elif op == 'Min':
            r = np.minimum(a[0], a[1])
        elif op == 'Pow':
            r = a[0] ** a[1]
        elif op == 'Neg':
            r = -a[0]
        elif op == 'Exp':
            r = np.exp(a[0])
        elif op == 'Log':
            r = np.log(a[0])
        elif op == 'Tanh':
            r = np.tanh(a[0])
        elif op == 'Sigmoid':
            r = 1.0 / (1.0 + np.exp(-a[0]))
        elif op == 'Erf':
            from scipy.special import erf as _erf  # pragma: no cover
            r = _erf(a[0])
        elif op == 'Sqrt':
            r = np.sqrt(a[0])
        elif op == 'Abs':
            r = np.abs(a[0])
        elif op == 'Identity':
            r = a[0]
        elif op == 'Reshape':
            r = a[0].reshape([int(d) for d in a[1]])
        elif op == 'Transpose':
            r = np.transpose(a[0], attrs['perm'])
        elif op == 'Expand':
            r = np.broadcast_to(a[0], [int(d) for d in a[1]]).copy()
        elif op == 'Unsqueeze':
            r = a[0]
            for ax in sorted(int(x) for x in a[1]):
                r = np.expand_dims(r, ax)
        elif op == 'Squeeze':
            r = np.squeeze(a[0], tuple(int(x) for x in a[1]))
        elif op == 'Concat':
            r = np.concatenate(a, axis=attrs['axis'])
        elif op == 'Slice':
            starts, ends, axes, steps = (a[1], a[2], a[3], a[4])
            sl = [slice(None)] * a[0].ndim
            for s, e2, ax, st in zip(starts, ends, axes, steps):
                e2 = int(e2)
                if e2 < -(2 ** 30):
                    e2 = None
                sl[int(ax)] = slice(int(s), e2, int(st))
            r = a[0][tuple(sl)]
        elif op == 'Cast':
            r = a[0].astype(_NP_OF_ONNX[attrs['to']])
        elif op == 'Where':
            r = np.where(a[0], a[1], a[2])
        elif op == 'Equal':
            r = a[0] == a[1]
        elif op == 'Less':
            r = a[0] < a[1]
        elif op == 'Greater':
            r = a[0] > a[1]
        elif op == 'GreaterOrEqual':
            r = a[0] >= a[1]
        elif op == 'LessOrEqual':
            r = a[0] <= a[1]
        elif op in ('ReduceSum', 'ReduceMax', 'ReduceMin'):
            axes = a[1] if len(a) > 1 else attrs['axes']
            fn = {'ReduceSum': np.sum, 'ReduceMax': np.max,
                  'ReduceMin': np.min}[op]
            r = fn(a[0], axis=tuple(int(x) for x in axes),
                   keepdims=bool(attrs.get('keepdims', 1)))
        elif op == 'Gather':
            r = np.take(a[0], a[1].astype(np.int64),
                        axis=attrs.get('axis', 0))
        else:
            raise NotImplementedError('evaluator op %s' % op)
        env[outs[0]] = r

    for op, ins, outs, attrs in nodes:
        ev(op, ins, outs, attrs)
    return [env[n] for n in g_out]


# -- tests -------------------------------------------------------------------

def test_export_mlp_matches_eager(tmp_path):
    paddle.seed(7)
    model = nn.Sequential(
        nn.Linear(16, 32), nn.Tanh(),
        nn.LayerNorm(32),
        nn.Linear(32, 8))
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 16).astype(np.float32))
    ref = model(x).numpy()

    out = paddle.onnx.export(model, str(tmp_path / 'mlp'),
                             input_spec=[InputSpec([4, 16], 'float32', 'x')])
    blob = open(out, 'rb').read()
    got = _run_onnx(blob, {'x': np.asarray(x.numpy())})
    np.testing.assert_allclose(got[0], ref, rtol=2e-5, atol=2e-5)


def test_export_tiny_gpt_matches_eager(tmp_path):
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
    paddle.seed(3)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=16, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 64, (2, 16)).astype(np.int32))
    ref = model(ids).numpy()

    out = paddle.onnx.export(
        model, str(tmp_path / 'gpt'),
        input_spec=[InputSpec([2, 16], 'int32', 'ids')])
    blob = open(out, 'rb').read()
    got = _run_onnx(blob, {'ids': np.asarray(ids.numpy())})
    np.testing.assert_allclose(got[0], ref, rtol=2e-4, atol=2e-4)


def test_export_unsupported_primitive_raises(tmp_path):
    class Sorter(nn.Layer):
        def forward(self, x):
            from paddle_tpu.tensor import search
            return search.sort(x)

    model = Sorter()
    with pytest.raises((NotImplementedError, Exception)) as ei:
        paddle.onnx.export(model, str(tmp_path / 'bad'),
                           input_spec=[InputSpec([4, 4], 'float32', 'x')])
    assert 'not supported' in str(ei.value) or 'sort' in str(ei.value)
