"""Resume-cursor determinism (ISSUE 14 satellite): seeded RNG streams
and data-loader position round-trip exactly through a checkpoint, so a
resumed run consumes the SAME batches in the SAME order as the
uninterrupted run."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.supervisor import (ResumeCursor,
                                               TrainingSupervisor)
from paddle_tpu.framework import io_save
from paddle_tpu.framework import random as prandom
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.hapi.model import Model
from paddle_tpu.io import DataLoader, Dataset


def test_rng_capture_restores_both_streams():
    """capture_rng/restore_rng must round-trip BOTH host RNG streams:
    the global numpy one and the framework.random generator key."""
    np.random.seed(42)
    paddle.seed(4242)
    np.random.rand(3)                  # advance both streams
    prandom.next_key()
    snap = ResumeCursor.capture_rng()
    a_np = np.random.rand(5)
    a_key = np.asarray(prandom.next_key())
    ResumeCursor.restore_rng(snap)
    b_np = np.random.rand(5)
    b_key = np.asarray(prandom.next_key())
    assert np.array_equal(a_np, b_np)
    assert np.array_equal(a_key, b_key)


def test_cursor_roundtrips_through_io_save(tmp_path):
    np.random.seed(1)
    cur = ResumeCursor(epoch=2, step=5, global_step=21,
                       epoch_rng=ResumeCursor.capture_rng(),
                       rng=ResumeCursor.capture_rng())
    path = str(tmp_path / 'cursor.ckpt')
    io_save.save(cur.to_state(), path)
    back = ResumeCursor.from_state(io_save.load(path))
    assert (back.epoch, back.step, back.global_step) == (2, 5, 21)
    ResumeCursor.restore_rng(back.rng)
    a = np.random.rand(4)
    ResumeCursor.restore_rng(cur.rng)
    assert np.array_equal(a, np.random.rand(4))


def test_shuffled_loader_order_replays_from_epoch_rng():
    """RandomSampler draws its permutation from the global numpy RNG
    when the iterator is built; re-seating the epoch-start capture must
    re-draw the identical shuffle."""

    class _Idx(Dataset):
        def __len__(self):
            return 20

        def __getitem__(self, i):
            return np.float32(i)

    np.random.seed(7)
    snap = ResumeCursor.capture_rng()
    loader = DataLoader(_Idx(), batch_size=4, shuffle=True)
    order1 = [tuple(np.asarray(b[0]).ravel()) for b in loader]
    ResumeCursor.restore_rng(snap)
    loader2 = DataLoader(_Idx(), batch_size=4, shuffle=True)
    order2 = [tuple(np.asarray(b[0]).ravel()) for b in loader2]
    assert order1 == order2
    # and it IS a shuffle, not identity order
    flat = [x for t in order1 for x in t]
    assert flat != sorted(flat)


class _TrackedData(Dataset):
    """Records every index the loader touches, in order — the witness
    for exact batch-order equality across an interrupted resume."""

    def __init__(self, n=24):
        rng = np.random.RandomState(3)
        self.x = rng.randn(n, 4).astype(np.float32)
        self.y = rng.randn(n, 1).astype(np.float32)
        self.accessed = []

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        self.accessed.append(int(i))
        return self.x[i], self.y[i]


def _build_model():
    paddle.seed(77)
    np.random.seed(55)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss())
    return m


def test_resumed_run_consumes_identical_batch_order(tmp_path):
    """Kill the trainer mid-epoch-1 and resume: the resumed run must
    walk exactly the uninterrupted run's index sequence from the top of
    the interrupted epoch (the fast-forwarded prefix re-reads the same
    shuffle; training restarts at the exact loader position)."""
    epochs, bs = 2, 4

    data_ref = _TrackedData()
    m_ref = _build_model()
    m_ref.fit(data_ref, batch_size=bs, epochs=epochs, shuffle=True,
              verbose=0)
    per_epoch = len(data_ref) // bs * bs    # indices touched per epoch

    class _Kill(Callback):
        def __init__(self):
            self.seen = 0

        def on_train_batch_end(self, step, logs=None):
            self.seen += 1
            if self.seen == 9:              # 3 steps into epoch 1
                raise KeyboardInterrupt()

    data_a = _TrackedData()
    m_a = _build_model()
    sup_a = TrainingSupervisor(str(tmp_path / 'ckpt'), save_every_steps=4)
    with pytest.raises(KeyboardInterrupt):
        m_a.fit(data_a, batch_size=bs, epochs=epochs, shuffle=True,
                verbose=0, supervisor=sup_a, callbacks=[_Kill()])
    assert sup_a.last_saved_step == 8        # epoch 1, step 2 cursor

    data_b = _TrackedData()
    m_b = _build_model()
    np.random.seed(1000)   # wrong seed: the cursor must restore order
    sup_b = TrainingSupervisor(str(tmp_path / 'ckpt'), save_every_steps=4)
    m_b.fit(data_b, batch_size=bs, epochs=epochs, shuffle=True,
            verbose=0, supervisor=sup_b)
    # resumed run re-reads the whole interrupted epoch (fast-forward
    # drains the trained prefix) — so its access log must equal the
    # reference run's from the top of epoch 1
    assert data_b.accessed == data_ref.accessed[per_epoch:]


def test_pipeline_kill_resume_is_bit_identical(tmp_path):
    """ISSUE 18: mid-epoch kill with a LIVE shuffle buffer (the resume
    position is not window aligned) through the streaming IngestPipeline
    + supervisor. Unlike the DataLoader path above, the pipeline does
    not re-read the trained prefix: the cursor SEEKS every shard reader,
    so the resumed record-access log must equal the reference run's
    suffix from the resumed window — and the final weights must be
    bit-identical to the uninterrupted run's."""
    from paddle_tpu.data import IngestPipeline, write_shards

    rng = np.random.RandomState(3)
    samples = [(x, y) for x, y in zip(rng.randn(48, 4).astype(np.float32),
                                      rng.randn(48, 1).astype(np.float32))]
    paths = write_shards(samples, str(tmp_path / 'shards'), 4)
    window, bs = 16, 4

    def run(trace, supervisor=None, callbacks=None):
        pipe = IngestPipeline(paths, batch_size=bs, shuffle_window=window,
                              seed=11, record_trace=trace)
        if supervisor is not None:
            supervisor.attach_pipeline(pipe)
        m = _build_model()
        m.fit(pipe, epochs=2, verbose=0, supervisor=supervisor,
              callbacks=callbacks)
        return m

    ref_trace = []
    m_ref = run(ref_trace)
    ref_params = [np.asarray(p) for p in m_ref.network.parameters()]

    class _Kill(Callback):
        seen = 0

        def on_train_batch_end(self, step, logs=None):
            _Kill.seen += 1
            if _Kill.seen == 19:         # 7 steps into epoch 1
                raise KeyboardInterrupt()

    trace_a = []
    sup_a = TrainingSupervisor(str(tmp_path / 'ckpt'), save_every_steps=1)
    with pytest.raises(KeyboardInterrupt):
        run(trace_a, supervisor=sup_a, callbacks=[_Kill()])
    # last on_step ran after batch 18 = epoch 1 step 6 -> 24 records
    assert sup_a.last_saved_step == 18

    trace_b = []
    np.random.seed(999)                  # wrong seed: the cursor must win
    sup_b = TrainingSupervisor(str(tmp_path / 'ckpt'), save_every_steps=1)
    m_b = run(trace_b, supervisor=sup_b)

    for a, b in zip(ref_params,
                    [np.asarray(p) for p in m_b.network.parameters()]):
        assert np.array_equal(a, b)
    # access log: 24 delivered records live in window 1, so the resumed
    # pipeline seeks the readers to stream position 16 of epoch 1 — the
    # reference epoch-1 trace (after its 48-record epoch 0) from there
    assert trace_b == ref_trace[48 + 16:]
