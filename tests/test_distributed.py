"""Distributed tests on the 8-device virtual CPU mesh (reference pattern:
TestDistBase localhost multi-process, SURVEY.md §4.2 — here: SPMD shard_map
and sharding-spec assertions replace process spawning)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn


def _mesh(shape, names):
    devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def test_eight_devices_present():
    assert len(jax.devices()) == 8


def test_topology_hcg():
    from paddle_tpu.distributed import HybridCommunicateGroup
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, sharding_degree=2)
    assert hcg.mesh.shape['dp'] == 2
    assert hcg.mesh.shape['mp'] == 2
    assert hcg.mesh.shape['sharding'] == 2
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2


def test_psum_inside_shard_map():
    from jax.experimental.shard_map import shard_map
    mesh = _mesh((8,), ('dp',))
    x = jnp.arange(8.0)

    def f(x):
        return jax.lax.psum(x, 'dp')

    out = shard_map(f, mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_dp_gradient_sync_via_jit():
    """Params replicated + batch sharded over dp => grads are global sums
    (what the reference's Reducer/allreduce achieves)."""
    mesh = _mesh((8,), ('dp',))
    w = jnp.ones((4, 2))
    x = np.random.RandomState(0).standard_normal((16, 4)).astype(np.float32)

    def loss(w, x):
        return jnp.sum((x @ w) ** 2)

    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P('dp')))
    ws = jax.device_put(w, NamedSharding(mesh, P()))
    g = jax.jit(jax.grad(loss))(ws, xs)
    g_ref = jax.grad(loss)(w, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-5)


def test_fleet_train_step_dp_matches_single():
    """Loss-parity harness: dp-sharded fleet step == single-device step
    (reference: test_dist_base.check_with_place loss comparison)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.framework.functional import TrainStep

    def build():
        paddle.seed(11)
        m = nn.Linear(8, 4)
        o = paddle.optimizer.Adam(learning_rate=1e-2,
                                  parameters=m.parameters())
        return m, o

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    loss_fn = nn.MSELoss()

    m1, o1 = build()
    s1 = TrainStep(m1, loss_fn, o1)
    l1 = [float(s1(x, y).numpy()) for _ in range(3)]

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
                               'sharding_degree': 1, 'sp_degree': 1}
    fleet.init(is_collective=True, strategy=strategy)
    m2, o2 = build()
    s2 = fleet.fleet_train_step(m2, loss_fn, o2, strategy=strategy)
    l2 = [float(s2(x, y).numpy()) for _ in range(3)]
    np.testing.assert_allclose(l1, l2, rtol=1e-4)


def test_fleet_zero3_matches_single():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.framework.functional import TrainStep

    def build():
        paddle.seed(13)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        o = paddle.optimizer.AdamW(learning_rate=1e-2, weight_decay=0.01,
                                   parameters=m.parameters())
        return m, o

    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    loss_fn = nn.MSELoss()

    m1, o1 = build()
    s1 = TrainStep(m1, loss_fn, o1)
    l1 = [float(s1(x, y).numpy()) for _ in range(3)]

    strategy = fleet.DistributedStrategy()
    strategy.sharding = True
    strategy.sharding_configs['stage'] = 3
    strategy.hybrid_configs = {'dp_degree': 2, 'mp_degree': 1, 'pp_degree': 1,
                               'sharding_degree': 4, 'sp_degree': 1}
    fleet.init(is_collective=True, strategy=strategy)
    m2, o2 = build()
    s2 = fleet.fleet_train_step(m2, loss_fn, o2, strategy=strategy)
    l2 = [float(s2(x, y).numpy()) for _ in range(3)]
    np.testing.assert_allclose(l1, l2, rtol=1e-4)
    # params really are sharded over the 'sharding' axis
    shardings = {n: p._data.sharding for n, p in m2.named_parameters()}
    assert any('sharding' in str(s.spec) for s in shardings.values())


def test_tp_layers_match_plain_linear():
    from paddle_tpu.distributed.meta_parallel import (ColumnParallelLinear,
                                                      RowParallelLinear)
    paddle.seed(5)
    col = ColumnParallelLinear(8, 16)
    row = RowParallelLinear(16, 8)
    x = paddle.randn([4, 8])
    mid = col(x)
    out = row(mid)
    ref_mid = x.numpy() @ col.weight.numpy() + col.bias.numpy()
    ref = ref_mid @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)
    assert col.weight.placement == (None, 'mp')
    assert row.weight.placement == ('mp', None)


def test_ring_attention_matches_full():
    from paddle_tpu.ops.ring_attention import ring_attention_sharded
    mesh = _mesh((8,), ('sp',))
    rng = np.random.RandomState(0)
    b, n, h, d = 2, 64, 4, 16
    q = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)

    def ref(q, k, v, causal):
        s = np.einsum('bqhd,bkhd->bhqk', q, k) / np.sqrt(d)
        if causal:
            mask = np.tril(np.ones((n, n), bool))
            s = np.where(mask[None, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum('bhqk,bkhd->bqhd', p, v)

    for causal in (False, True):
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   ref(np.asarray(q), np.asarray(k),
                                       np.asarray(v), causal),
                                   atol=2e-4,
                                   err_msg='causal=%s' % causal)


def test_ulysses_attention_matches_full():
    from paddle_tpu.ops.ring_attention import ulysses_attention_sharded
    mesh = _mesh((8,), ('sp',))
    rng = np.random.RandomState(1)
    # h=16 over sp=8 gives 2 local heads per device — exercises the
    # head-reconstruction order in head2seq (regression: heads were
    # permuted whenever h/sp > 1)
    b, n, h, d = 2, 64, 16, 16
    q = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)

    s = np.einsum('bqhd,bkhd->bhqk', np.asarray(q), np.asarray(k)) / np.sqrt(d)

    def ref_of(scores, causal):
        if causal:
            mask = np.tril(np.ones((n, n), bool))
            scores = np.where(mask[None, None], scores, -1e30)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum('bhqk,bkhd->bqhd', p, np.asarray(v))

    for causal in (False, True):
        out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), ref_of(s, causal),
                                   atol=2e-4, err_msg='causal=%s' % causal)


def test_collective_api_world1_identity():
    import paddle_tpu.distributed as dist
    x = paddle.to_tensor([1., 2.])
    dist.all_reduce(x)
    np.testing.assert_allclose(x.numpy(), [1., 2.])
    out = []
    dist.all_gather(out, x)
    assert len(out) == 1


def test_dryrun_multichip_entry():
    import sys
    sys.path.insert(0, '/root/repo')
    if jax.default_backend() == 'cpu':
        # the 8-device factorization includes pp configs, which hit
        # XLA:CPU's SPMD partitioner gap ("UNIMPLEMENTED: PartitionId
        # instruction is not supported for SPMD partitioning"). The
        # 2-device run drives the same dryrun surface — sharding audit,
        # telemetry/fleet snapshots, and the wide-event line — through
        # the dp/mp/sharding primary config only. It runs in a child
        # process: dryrun_multichip must be the first JAX use in its
        # process for the CPU device-count override to take effect, and
        # this process already holds the suite's 8-device backend.
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('XLA_FLAGS', None)
        proc = subprocess.run(
            [sys.executable, '-c',
             'import __graft_entry__ as g; g.dryrun_multichip(2)'],
            cwd='/root/repo', env=env, capture_output=True, text=True,
            timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        from paddle_tpu.monitor import events as _ev
        assert _ev.parse_event_lines(proc.stdout), proc.stdout
    else:
        import __graft_entry__ as g
        g.dryrun_multichip(8)


def test_embedding_service_local_cluster():
    """Same-process PS cluster (reference: brpc_service_dense_sgd_test.cc
    pattern)."""
    from paddle_tpu.distributed.ps.runtime import local_cluster
    servers, client = local_cluster(num_servers=2, dim=4, optimizer='sgd',
                                    lr=0.5)
    ids = np.asarray([1, 5, 9, 1])
    rows = client.pull(0, ids)
    assert rows.shape == (4, 4)
    np.testing.assert_allclose(rows[0], rows[3])  # same id, same row
    grads = np.ones((4, 4), np.float32)
    client.push(0, ids, grads)
    rows2 = client.pull(0, ids)
    # id 1 appears twice: two grads applied
    np.testing.assert_allclose(rows2[0], rows[0] - 0.5 * 2, atol=1e-6)
    np.testing.assert_allclose(rows2[1], rows[1] - 0.5, atol=1e-6)
    for s in servers:
        s.stop()


def test_embedding_service_socket_transport():
    from paddle_tpu.distributed.ps.embedding_service import (EmbeddingServer,
                                                             EmbeddingClient)
    srv = EmbeddingServer()
    srv.create_table(0, dim=3, optimizer='adagrad', lr=0.1)
    srv.start(block=False)
    client = EmbeddingClient(endpoints=['127.0.0.1:%d' % srv.port])
    ids = np.asarray([7, 8])
    rows = client.pull(0, ids)
    assert rows.shape == (2, 3)
    client.push(0, ids, np.ones((2, 3), np.float32))
    rows2 = client.pull(0, ids)
    assert not np.allclose(rows, rows2)
    srv.stop()


def test_sync_batchnorm_global_stats_under_dp():
    """SyncBatchNorm's contract — BN statistics span the GLOBAL batch —
    holds under pjit dp sharding (the class doc's 'implicit sync' claim):
    running mean after one step equals the global batch mean, not any
    per-shard mean (reference sync_batch_norm_op.cu semantics)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.framework import functional as func_mod

    paddle.seed(0)
    bn = nn.SyncBatchNorm(3)
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ('dp',))

    rng = np.random.RandomState(0)
    # per-shard means differ strongly: shard i gets offset i
    x = rng.randn(16, 3, 4, 4).astype(np.float32)
    x += np.repeat(np.arange(8), 2)[:, None, None, None]

    params = func_mod.extract_params(bn)
    buffers = func_mod.extract_buffers(bn)

    def step(params, buffers, xb):
        out, new_buf = func_mod.functional_call(bn, params, buffers,
                                                args=(xb,), training=True)
        return out, new_buf

    xb = jax.device_put(x, NamedSharding(mesh, P('dp')))
    out, new_buf = jax.jit(step)(params, buffers, xb)

    global_mean = x.mean(axis=(0, 2, 3))
    momentum = bn._momentum
    expect = (1 - momentum) * global_mean  # running mean starts at 0
    got = np.asarray(new_buf['_mean'])
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)
    # the normalized output is standardized over the GLOBAL batch
    o = np.asarray(out)
    np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), 0.0, atol=1e-4)


def test_fleet_wrapper_behaviors(tmp_path):
    """Former pass-bodies now act: distributed_model pre-places params on
    the fleet mesh, save_persistables writes the model state, and
    DataParallel registers with fleet + validates its input."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.parallel import DataParallel

    fleet._FLEET['model'] = None
    fleet.init(is_collective=True)
    net = nn.Linear(4, 3)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    fleet.distributed_optimizer(opt)
    fleet.distributed_model(net)
    # params now live on the hcg mesh (placed, not host-committed)
    sh = net.weight._data.sharding
    assert set(getattr(sh, 'mesh', None).axis_names) >= {'dp'}

    out_dir = str(tmp_path / 'persist')
    fleet.save_persistables(None, out_dir)
    import os
    assert os.path.exists(os.path.join(out_dir, 'persistables.pdparams'))
    state = paddle.load(os.path.join(out_dir, 'persistables.pdparams'))
    np.testing.assert_allclose(np.asarray(state['weight']),
                               net.weight.numpy())

    fleet.barrier_worker()  # no PS service: must be a clean no-op

    fleet._FLEET['model'] = None
    dp = DataParallel(net)
    assert fleet._FLEET['model'] is net
    with dp.no_sync():
        pass
    with pytest.raises(TypeError):
        DataParallel('not a layer')


@pytest.mark.slow
def test_ring_attention_long_context_8k():
    """Long-context evidence: seq 8192 sharded sp=8 (1024 tokens/device)
    through ring attention, fwd + grads, against a blocked numpy
    reference. The full [n, n] score matrix (8192^2 = 67M entries per
    head) never materializes on any one device."""
    from paddle_tpu.ops.ring_attention import ring_attention_sharded
    mesh = _mesh((8,), ('sp',))
    rng = np.random.RandomState(0)
    b, n, h, d = 1, 8192, 1, 8
    q = jnp.asarray(rng.standard_normal((b, n, h, d)) * 0.2, jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, h, d)) * 0.2, jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, h, d)) * 0.2, jnp.float32)

    out = ring_attention_sharded(q, k, v, mesh, causal=True)

    # blocked reference (numpy, streaming over k-chunks to stay small)
    qf = np.asarray(q[0, :, 0]); kf = np.asarray(k[0, :, 0])
    vf = np.asarray(v[0, :, 0])
    scale = 1.0 / np.sqrt(d)
    m = np.full(n, -np.inf); l = np.zeros(n); acc = np.zeros((n, d))
    for start in range(0, n, 1024):
        kb = kf[start:start + 1024]; vb = vf[start:start + 1024]
        s = qf @ kb.T * scale
        col = np.arange(start, start + 1024)
        s = np.where(col[None, :] <= np.arange(n)[:, None], s, -np.inf)
        m_new = np.maximum(m, s.max(-1))
        p = np.exp(s - m_new[:, None])
        corr = np.exp(m - m_new)
        l = l * corr + p.sum(-1)
        acc = acc * corr[:, None] + p @ vb
        m = m_new
    ref_out = acc / l[:, None]
    np.testing.assert_allclose(np.asarray(out)[0, :, 0], ref_out,
                               atol=3e-4)

    # gradients flow through the ring
    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True)
                       .astype(jnp.float32) ** 2)
    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # check dq against the dense jnp reference gradient (fits on CPU)
    def dense_loss(q, k, v):
        s = jnp.einsum('bqhd,bkhd->bhqk', q, k) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum('bhqk,bkhd->bqhd', p, v)
        return jnp.sum(o ** 2)
    dq_ref = jax.grad(dense_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(dq_ref),
                               atol=3e-4)
    for gi in g[1:]:
        arr = np.asarray(gi)
        assert np.isfinite(arr).all() and np.abs(arr).max() > 0


def test_fleet_zero3_bf16_multi_precision():
    """ZeRO-3 composes with a bf16 model and multi_precision masters: the
    f32 master/slot entries ride the sharded opt-state pytree, stored
    params stay bf16 AND sharded, and training stays finite."""
    from paddle_tpu.distributed import fleet

    paddle.seed(23)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.bfloat16()
    o = paddle.optimizer.AdamW(learning_rate=1e-2, weight_decay=0.01,
                               multi_precision=True,
                               parameters=m.parameters())

    def loss_fn(out, lab):
        return paddle.nn.functional.mse_loss(out.astype('float32'), lab)

    strategy = fleet.DistributedStrategy()
    strategy.sharding = True
    strategy.sharding_configs['stage'] = 3
    strategy.hybrid_configs = {'dp_degree': 2, 'mp_degree': 1,
                               'pp_degree': 1, 'sharding_degree': 4,
                               'sp_degree': 1}
    fleet.init(is_collective=True, strategy=strategy)
    step = fleet.fleet_train_step(m, loss_fn, o, strategy=strategy)

    rng = np.random.RandomState(4)
    x = paddle.to_tensor(
        rng.standard_normal((16, 8)).astype(np.float32)).astype('bfloat16')
    y = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    losses = [float(step(x, y).numpy()) for _ in range(3)]
    assert all(np.isfinite(losses)), losses

    for n, p in m.named_parameters():
        assert p.dtype == paddle.bfloat16, n
    shardings = {n: p._data.sharding for n, p in m.named_parameters()}
    assert any('sharding' in str(s.spec) for s in shardings.values())
    # masters exist, are f32, were WRITTEN BACK by the jitted step (a
    # lazily re-created slot would have all-zero moments), and ride the
    # sharded opt-state pytree
    import jax.numpy as jnp
    pmap = dict(m.named_parameters())
    for n, p in pmap.items():
        slots = o._get_slots(p)
        if not p.stop_gradient:
            assert slots['master'].dtype == jnp.float32, n
            assert slots['moment1'].dtype == jnp.float32, n
            assert np.abs(np.asarray(slots['moment1'])).max() > 0, n
    assert any('sharding' in str(o._get_slots(p)['master'].sharding.spec)
               for p in pmap.values() if not p.stop_gradient)
