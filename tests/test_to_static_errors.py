"""to_static error ergonomics (VERDICT r3 item 7): a trace-time failure
must point at the USER's file:line with a lax-helper hint, not surface as
a raw JAX internals stack (reference: dygraph_to_static/error.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.error import ToStaticError


def test_data_dependent_branch_points_at_user_line():
    @paddle.jit.to_static
    def bad(x):
        s = paddle.sum(x)
        if s > 0:                      # <- traced bool: untraceable
            return x + 1
        return x - 1

    x = paddle.to_tensor(np.ones((3,), np.float32))
    bad(x)  # first call runs eagerly (recorded) — fine
    with pytest.raises(ToStaticError) as ei:
        bad(paddle.to_tensor(np.ones((3,), np.float32)))
    msg = str(ei.value)
    assert __file__.rstrip('c') in msg          # user file
    assert 'if s > 0:' in msg                   # offending source line
    assert 'cond' in msg                        # the lax-helper hint
    assert ei.value.__cause__ is not None       # original chained


def test_layer_method_trace_error_points_at_user_line():
    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(4, 4)

        @paddle.jit.to_static
        def forward(self, x):
            y = self.lin(x)
            n = int(paddle.sum(y))     # <- traced int conversion
            return y * n

    m = M()
    with pytest.raises(ToStaticError) as ei:
        m(paddle.to_tensor(np.ones((2, 4), np.float32)))
    msg = str(ei.value)
    assert __file__.rstrip('c') in msg
    assert 'int(paddle.sum(y))' in msg


def test_successful_to_static_unaffected():
    @paddle.jit.to_static
    def good(x):
        return paddle.nn.functional.relu(x) * 2

    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    np.testing.assert_allclose(good(x).numpy(), [0.0, 4.0])
    np.testing.assert_allclose(good(x).numpy(), [0.0, 4.0])  # jit cache


def test_non_jax_user_errors_propagate_unwrapped():
    @paddle.jit.to_static
    def boom(x):
        raise KeyError('user bug')

    with pytest.raises(KeyError, match='user bug'):
        boom(paddle.to_tensor(np.ones((2,), np.float32)))
