"""Fleet meta-optimizer strategy tests (reference pattern:
test_fleet_*_meta_optimizer.py — enable a strategy flag, then assert on the
transformed program; here: build the step and assert behavior/numerics)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_optimizers import (ShardMapDPStep,
                                                          dgc_compress,
                                                          select_optimizer)


def _model_and_data(seed=0, n=64, din=16, dout=4):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(din, 32), nn.ReLU(), nn.Linear(32, dout))
    rng = np.random.RandomState(seed)
    x = rng.standard_normal((n, din)).astype(np.float32)
    y = rng.randint(0, dout, (n,)).astype(np.int64)
    return model, x, y


def _loss_fn(logits, labels):
    return nn.functional.cross_entropy(logits, labels)


def test_gradient_merge_matches_big_batch():
    # k merged micro-batches with avg ≡ one step on the concatenated batch
    model1, x, y = _model_and_data()
    opt1 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=model1.parameters())
    from paddle_tpu.framework.functional import TrainStep
    step1 = TrainStep(model1, _loss_fn, opt1, k_steps=4, donate=False)
    for i in range(4):
        loss = step1(paddle.to_tensor(x[i * 16:(i + 1) * 16]),
                     paddle.to_tensor(y[i * 16:(i + 1) * 16]))
    p_merged = {k: np.asarray(v) for k, v in
                __import__('paddle_tpu.framework.functional',
                           fromlist=['extract_params']
                           ).extract_params(model1).items()}

    model2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=model2.parameters())
    step2 = TrainStep(model2, _loss_fn, opt2, donate=False)
    step2(paddle.to_tensor(x), paddle.to_tensor(y))
    from paddle_tpu.framework.functional import extract_params
    p_big = {k: np.asarray(v) for k, v in extract_params(model2).items()}
    for k in p_merged:
        np.testing.assert_allclose(p_merged[k], p_big[k], rtol=2e-4,
                                   atol=1e-5, err_msg=k)
    assert opt1._step_count == 1  # one real optimizer step


def test_gradient_merge_no_update_midway():
    model, x, y = _model_and_data()
    from paddle_tpu.framework.functional import TrainStep, extract_params
    before = {k: np.asarray(v)
              for k, v in extract_params(model).items()}
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = TrainStep(model, _loss_fn, opt, k_steps=3, donate=False)
    step(paddle.to_tensor(x[:8]), paddle.to_tensor(y[:8]))
    step(paddle.to_tensor(x[8:16]), paddle.to_tensor(y[8:16]))
    after = {k: np.asarray(v) for k, v in extract_params(model).items()}
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])


def test_shardmap_dense_matches_pjit_dp():
    model1, x, y = _model_and_data(seed=3)
    opt1 = paddle.optimizer.SGD(learning_rate=0.05,
                                parameters=model1.parameters())
    dstep = ShardMapDPStep(model1, _loss_fn, opt1, mode='dense')
    l1 = float(dstep(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())

    model2, _, _ = _model_and_data(seed=3)
    opt2 = paddle.optimizer.SGD(learning_rate=0.05,
                                parameters=model2.parameters())
    from paddle_tpu.framework.functional import TrainStep, extract_params
    tstep = TrainStep(model2, _loss_fn, opt2, donate=False)
    l2 = float(tstep(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
    assert abs(l1 - l2) < 1e-4
    p1 = extract_params(model1)
    p2 = extract_params(model2)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_shardmap_fp16_allreduce_close_to_dense():
    model, x, y = _model_and_data(seed=4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    step = ShardMapDPStep(model, _loss_fn, opt, mode='fp16')
    losses = [float(step(paddle.to_tensor(x),
                         paddle.to_tensor(y)).numpy()) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_dgc_compress_semantics():
    g = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05, 0.0, 1.0, -0.3])
    u0 = jnp.zeros(8)
    v0 = jnp.zeros(8)
    send, u, v = dgc_compress(g, u0, v0, momentum=0.9, sparsity=0.75)
    # 25% of 8 = 2 entries transmitted: the top-|.| ones (−5, 3)
    assert int(jnp.count_nonzero(send)) == 2
    assert float(send[1]) == -5.0 and float(send[3]) == 3.0
    # residual keeps untransmitted mass, transmitted entries cleared
    assert float(v[1]) == 0.0 and float(v[0]) == pytest.approx(0.1)
    # a small gradient accumulates until it crosses the threshold
    small = jnp.asarray([1.2, 0., 0., 0., 0., 0., 0., 0.])
    send2, u2, v2 = dgc_compress(small, u, v, momentum=0.9, sparsity=0.75)
    assert float(send2[0]) != 0.0  # error feedback pushed it through


def test_dgc_training_converges():
    model, x, y = _model_and_data(seed=5)
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=model.parameters())
    step = ShardMapDPStep(model, _loss_fn, opt, mode='dgc', sparsity=0.9)
    losses = [float(step(paddle.to_tensor(x),
                         paddle.to_tensor(y)).numpy()) for _ in range(12)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_localsgd_syncs_every_k():
    model, x, y = _model_and_data(seed=6)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    step = ShardMapDPStep(model, _loss_fn, opt, mode='local', k_steps=2)
    losses = [float(step(paddle.to_tensor(x),
                         paddle.to_tensor(y)).numpy()) for _ in range(6)]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
    # after an even number of steps replicas were just averaged: the
    # stacked params must be identical across the dp axis
    stacked = step._state['params']
    for name, arr in stacked.items():
        a = np.asarray(arr)
        assert np.allclose(a, a[:1]), name


def test_fleet_strategy_routing_and_optimizer_swap():
    s = fleet.DistributedStrategy()
    s.lamb = True
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=[])
    swapped = select_optimizer(opt, s)
    assert type(swapped).__name__ == 'Lamb'

    s2 = fleet.DistributedStrategy()
    s2.lars = True
    opt2 = paddle.optimizer.Momentum(learning_rate=0.1, parameters=[])
    swapped2 = select_optimizer(opt2, s2)
    assert type(swapped2).__name__ == 'LarsMomentum'


def test_fleet_train_step_localsgd_route():
    model, x, y = _model_and_data(seed=7)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    s = fleet.DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs['k_steps'] = 2
    fleet.init(is_collective=True, strategy=s)
    step = fleet.fleet_train_step(model, _loss_fn, opt, strategy=s)
    assert isinstance(step, ShardMapDPStep)
    loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert np.isfinite(float(loss.numpy()))


def test_lars_momentum_update_rule():
    paddle.seed(1)
    p0 = np.asarray([[3.0, 4.0]], np.float32)  # ||p||=5
    lin = nn.Linear(2, 1)
    lin.weight.set_value(p0.T)
    opt = paddle.optimizer.LarsMomentum(
        learning_rate=0.1, momentum=0.0, lars_coeff=0.01,
        lars_weight_decay=0.0, parameters=[lin.weight])
    g = np.asarray([[1.0], [0.0]], np.float32)  # ||g||=1
    lin.weight._grad = __import__('paddle_tpu').to_tensor(g)
    opt.step()
    # local_lr = 0.1 * 0.01 * 5 / 1 = 0.005; p -= local_lr * g
    expect = p0.T - 0.005 * g
    np.testing.assert_allclose(np.asarray(lin.weight._data), expect,
                               rtol=1e-5)


def test_lars_exclusion_plain_momentum():
    paddle.seed(2)
    lin = nn.Linear(2, 2)
    bias = lin.bias
    opt = paddle.optimizer.LarsMomentum(
        learning_rate=0.1, momentum=0.0, lars_coeff=0.01,
        lars_weight_decay=0.5, parameters=[lin.weight, lin.bias],
        exclude_from_weight_decay=[bias.name])
    g = np.asarray([1.0, 2.0], np.float32)
    b0 = np.asarray(bias._data).copy()
    bias._grad = paddle.to_tensor(g)
    lin.weight._grad = paddle.to_tensor(
        np.zeros(lin.weight.shape, np.float32))
    opt.step()
    # excluded: plain momentum step, NO lars scaling or weight decay
    np.testing.assert_allclose(np.asarray(bias._data), b0 - 0.1 * g,
                               rtol=1e-6)


def test_dgc_rampup_dense_then_sparse():
    model, x, y = _model_and_data(seed=8)
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=model.parameters())
    step = ShardMapDPStep(model, _loss_fn, opt, mode='dgc', sparsity=0.999,
                          rampup_begin_step=2, rampup_step=4)
    assert step._current_sparsity() is None           # warmup: dense
    for _ in range(2):
        step(paddle.to_tensor(x), paddle.to_tensor(y))
    s2 = step._current_sparsity()
    assert s2 is not None and s2 < 0.999              # climbing the ladder
    for _ in range(5):
        step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert step._current_sparsity() == 0.999          # reached target


def test_adaptive_localsgd_adjusts_k():
    model, x, y = _model_and_data(seed=9)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    step = ShardMapDPStep(model, _loss_fn, opt, mode='local', k_steps=1,
                          adaptive=True)
    ks = []
    for _ in range(6):
        step(paddle.to_tensor(x), paddle.to_tensor(y))
        ks.append(step.k_steps)
    # loss decreases on this toy problem, so the sync period must widen
    assert ks[-1] > 1, ks


def test_fleet_train_step_strategy_mismatch_consistent():
    # regression: sharding/step config must derive from the SAME strategy
    model, x, y = _model_and_data(seed=10)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    init_s = fleet.DistributedStrategy()          # no gradient merge
    fleet.init(is_collective=True, strategy=init_s)
    s = fleet.DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs['k_steps'] = 2
    step = fleet.fleet_train_step(model, _loss_fn, opt, strategy=s)
    l1 = step(paddle.to_tensor(x[:16]), paddle.to_tensor(y[:16]))
    l2 = step(paddle.to_tensor(x[16:32]), paddle.to_tensor(y[16:32]))
    assert np.isfinite(float(l2.numpy()))
    assert opt._step_count == 1  # merged: one applied step after 2 micros
