"""Go inference API end-to-end (reference pattern: the goapi demo
tests — paddle/fluid/inference/goapi run against a saved model).

Same shape as tests/test_capi.py, with the client swapped for the cgo
wrapper in paddle_tpu/capi/goapi: build libpaddle_tpu_c.so, `go build`
the demo client against it, run it on a jit.save'd model, and compare
the printed outputs with the in-process Python predictor.

Skips when the container has no Go toolchain (the shim is exercised in
CI images that carry one).
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOAPI_DIR = os.path.join(REPO, 'paddle_tpu', 'capi', 'goapi')


@pytest.fixture(scope='module')
def go_bin():
    path = shutil.which('go')
    if path is None:
        pytest.skip('go toolchain not installed')
    return path


@pytest.fixture(scope='module')
def capi_lib():
    from paddle_tpu.capi import build_capi
    try:
        return build_capi()
    except RuntimeError as e:
        pytest.skip('capi build unavailable: %s' % e)


@pytest.fixture(scope='module')
def saved_model(tmp_path_factory):
    paddle.seed(1234)
    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    model.eval()
    path = str(tmp_path_factory.mktemp('goapi') / 'mlp')
    from paddle_tpu.static import InputSpec
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([2, 8], name='features')])
    x = (0.125 * (np.arange(16, dtype=np.float32) - 8)).reshape(2, 8)
    ref = model(paddle.to_tensor(x)).numpy()
    return path, ref


@pytest.fixture(scope='module')
def demo_client(go_bin, capi_lib, tmp_path_factory):
    from paddle_tpu.capi import header_path
    exe = str(tmp_path_factory.mktemp('gobuild') / 'demo_client')
    env = dict(os.environ)
    env['CGO_ENABLED'] = '1'
    env['CGO_CFLAGS'] = '-I' + os.path.dirname(header_path())
    env['CGO_LDFLAGS'] = ('-L%s -lpaddle_tpu_c -Wl,-rpath,%s'
                          % (os.path.dirname(capi_lib),
                             os.path.dirname(capi_lib)))
    env.setdefault('GOFLAGS', '-mod=mod')
    env.setdefault('GOCACHE', str(tmp_path_factory.mktemp('gocache')))
    proc = subprocess.run([go_bin, 'build', '-o', exe, './cmd/demo'],
                          cwd=GOAPI_DIR, capture_output=True, text=True,
                          env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr
    return exe


def test_go_client_matches_python_predictor(demo_client, saved_model):
    model_path, ref = saved_model
    env = dict(os.environ)
    env['PYTHONPATH'] = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    env.pop('XLA_FLAGS', None)  # no virtual-device mesh inside the client
    proc = subprocess.run([demo_client, REPO, model_path],
                          capture_output=True, text=True, env=env,
                          timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    lines = proc.stdout.strip().splitlines()
    rank = int(lines[0].split()[1])
    dims = [int(l.split()[1]) for l in lines[1:1 + rank]]
    vals = np.array([float(l) for l in lines[1 + rank:]], np.float32)
    assert dims == list(ref.shape)
    np.testing.assert_allclose(vals.reshape(ref.shape), ref,
                               rtol=1e-5, atol=1e-6)


def test_go_client_reports_bad_model_path(demo_client, tmp_path):
    env = dict(os.environ)
    env['PYTHONPATH'] = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    env.pop('XLA_FLAGS', None)
    proc = subprocess.run([demo_client, REPO, str(tmp_path / 'nope')],
                          capture_output=True, text=True, env=env,
                          timeout=600)
    # NewPredictor must fail through PD_GetLastError, not crash
    assert proc.returncode == 4, (proc.returncode, proc.stderr)
    assert proc.stderr.strip()


def test_go_sources_present_and_wrap_full_surface():
    """Static check (runs even without a Go toolchain): the shim wraps
    every PD_* entry point in the header."""
    with open(os.path.join(REPO, 'paddle_tpu', 'capi', 'pd_capi.h')) as f:
        header = f.read()
    import re
    entries = set(re.findall(r'\b(PD_[A-Za-z]+)\s*\(', header))
    with open(os.path.join(GOAPI_DIR, 'paddle.go')) as f:
        shim = f.read()
    missing = {e for e in entries if 'C.%s(' % e not in shim}
    assert not missing, 'goapi does not wrap: %s' % sorted(missing)
    assert os.path.exists(os.path.join(GOAPI_DIR, 'cmd', 'demo', 'main.go'))
    assert os.access(os.path.join(GOAPI_DIR, 'run_demo.sh'), os.X_OK)
