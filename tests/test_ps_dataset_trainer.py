"""Dataset-driven PS training loop (VERDICT r2 missing #2 / item 6) and
the heterogeneous host-embedding + device-dense split (missing #1 / item 7).

Reference parity: framework/executor.cc:152 Executor::RunFromDataset,
device_worker.h:244/275 Hogwild/DownpourWorker TrainFiles,
framework/fleet/heter_ps/heter_comm.h:50 (CPU<->accelerator exchange).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps.embedding_service import (EmbeddingServer,
                                                         EmbeddingClient)
from paddle_tpu.distributed.ps.communicator import (AsyncCommunicator,
                                                    SyncCommunicator)
from paddle_tpu.distributed.ps.dataset import MultiSlotDataset
from paddle_tpu.distributed.ps.trainer import DownpourTrainer
from paddle_tpu.distributed.ps.tables import SsdSparseTable


def _write_ctr_files(tmp_path, n_files=4, lines_per_file=64, seed=0):
    """MultiSlot CTR data: 2 sparse slots + float label. The label is
    learnable: y=1 iff slot0 contains an id < 32."""
    rng = np.random.RandomState(seed)
    files = []
    for fi in range(n_files):
        path = tmp_path / ('part-%03d' % fi)
        with open(path, 'w') as f:
            for _ in range(lines_per_file):
                n0 = rng.randint(1, 4)
                pos = rng.rand() < 0.5
                lo, hi = (0, 32) if pos else (32, 128)
                s0 = rng.randint(lo, hi, n0)
                n1 = rng.randint(1, 3)
                s1 = rng.randint(0, 64, n1)
                label = 1.0 if pos else 0.0
                f.write('%d %s %d %s 1 %.1f\n' % (
                    n0, ' '.join(map(str, s0)),
                    n1, ' '.join(map(str, s1)), label))
        files.append(str(path))
    return files


def _make_cluster(optimizer='adagrad', lr=0.5, table_cls=None, **tkw):
    server = EmbeddingServer()
    server.create_table(0, dim=8, optimizer=optimizer, lr=lr,
                        init_scale=0.1, table_class=table_cls, **tkw)
    server.create_table(1, dim=8, optimizer=optimizer, lr=lr,
                        init_scale=0.1, table_class=table_cls, **tkw)
    client = EmbeddingClient(servers=[server])
    return server, client


def test_run_from_dataset_ctr_loss_decreases(tmp_path):
    files = _write_ctr_files(tmp_path)
    ds = MultiSlotDataset()
    ds.set_use_var([('slot0', 'int64'), ('slot1', 'int64'),
                    ('label', 'float32')])
    ds.set_filelist(files)
    ds.set_batch_size(16)
    ds.set_thread(2)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 256
    ds.local_shuffle(seed=1)

    server, client = _make_cluster()
    comm = AsyncCommunicator(client)
    comm.start()
    trainer = DownpourTrainer(client, comm, slots=['slot0', 'slot1'],
                              tables={'slot0': 0, 'slot1': 1},
                              emb_dim=8, hidden=16, lr=0.3, n_threads=2)
    try:
        first = trainer.train_from_dataset(ds, epochs=1)
        for _ in range(4):
            last = trainer.train_from_dataset(ds, epochs=1)
    finally:
        comm.stop()
    assert np.mean(last) < np.mean(first) * 0.8, (np.mean(first),
                                                  np.mean(last))
    # embeddings actually trained server-side
    assert len(server.table(0)) > 0


def test_run_from_dataset_sync_mode(tmp_path):
    files = _write_ctr_files(tmp_path, n_files=2)
    ds = MultiSlotDataset()
    ds.set_use_var([('slot0', 'int64'), ('slot1', 'int64'),
                    ('label', 'float32')])
    ds.set_filelist(files)
    ds.set_batch_size(16)
    ds.load_into_memory()
    server, client = _make_cluster()
    comm = SyncCommunicator(client)
    trainer = DownpourTrainer(client, comm, slots=['slot0', 'slot1'],
                              tables={'slot0': 0, 'slot1': 1},
                              emb_dim=8, hidden=16, lr=0.3, n_threads=1)
    first = trainer.train_from_dataset(ds, epochs=1)
    last = trainer.train_from_dataset(ds, epochs=3)
    assert np.mean(last[-8:]) < np.mean(first)


def test_heter_embedding_trains_under_jit():
    """HeterEmbedding: host table + jitted dense half, grads pushed back
    per step through the callback pair; loss decreases and the program
    exchanges only O(batch) rows (jaxpr has the callback, not the table)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.ps.heter import HeterEmbedding
    from paddle_tpu.framework import functional as func_mod

    server, client = _make_cluster(lr=0.3)

    paddle.seed(0)

    class CTRNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = HeterEmbedding(client, table_id=0, embedding_dim=8)
            self.fc = nn.Linear(8, 1)

        def forward(self, ids):
            e = self.emb(ids)           # [B, L, 8]
            from paddle_tpu.tensor import math as tmath
            pooled = tmath.mean(e, axis=1)
            return self.fc(pooled)

    model = CTRNet()
    opt = paddle.optimizer.SGD(learning_rate=0.2,
                               parameters=model.parameters())

    import paddle_tpu.nn.functional as F

    def loss_fn(logit, y):
        return F.binary_cross_entropy_with_logits(logit, y)

    step = func_mod.TrainStep(model, loss_fn, opt, donate=False)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (32, 3)).astype(np.int32)
    y = (ids.min(axis=1, keepdims=True) < 24).astype(np.float32)
    ids_t = paddle.to_tensor(ids)
    y_t = paddle.to_tensor(y)

    jaxpr = step.trace_jaxpr(ids_t, y_t)
    assert 'callback' in jaxpr  # the host exchange is in the program
    losses = [float(step(ids_t, y_t).numpy()) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    # the table stayed host-side and was trained by pushed grads
    assert len(server.table(0)) > 0


def test_heter_embedding_ssd_spill_table():
    """Memory claim: the table can exceed the in-memory hot set (SSD
    tier) while the device program stays O(batch) — more rows than
    max_mem_rows live correctly across the spill."""
    server = EmbeddingServer()
    server.create_table(0, dim=4, optimizer='sgd', lr=0.1,
                        table_class=SsdSparseTable, max_mem_rows=256)
    client = EmbeddingClient(servers=[server])

    # touch 2048 ids -> 8x the hot set; spill must preserve rows
    ids = np.arange(2048, dtype=np.int64)
    rows = client.pull(0, ids)
    assert rows.shape == (2048, 4)
    table = server.table(0)
    assert len(table._rows) <= 256  # hot set bounded
    # update a cold row and read it back through the tiering
    client.push(0, ids[:4], np.ones((4, 4), np.float32))
    rows2 = client.pull(0, ids[:4])
    assert not np.allclose(rows2, rows[:4])


def test_wire_codec_roundtrip_and_safety():
    """PS transport codec (VERDICT r2 weak #9): typed frames, no pickle —
    decode can never instantiate arbitrary objects."""
    from paddle_tpu.distributed.ps import wire
    msg = {'op': 'push', 'table': 3, 'ids': np.arange(5, dtype=np.int64),
           'grads': np.ones((5, 4), np.float32), 'note': 'hi',
           'flags': [True, False, None, 1.5], 'tup': (1, 'a')}
    out = wire.decode(wire.encode(msg))
    assert out['op'] == 'push' and out['table'] == 3
    np.testing.assert_array_equal(out['ids'], msg['ids'])
    np.testing.assert_array_equal(out['grads'], msg['grads'])
    assert out['flags'] == [True, False, None, 1.5]
    assert out['tup'] == (1, 'a')

    import pickle
    with pytest.raises(ValueError):
        wire.decode(pickle.dumps({'op': 'pull'}))  # pickle bytes rejected
    with pytest.raises(TypeError):
        wire.encode({'bad': object()})             # open types rejected


def test_embedding_service_over_sockets_uses_wire():
    """Full RPC path (same-process server on a localhost port, the
    reference brpc_service test style) over the typed codec."""
    server = EmbeddingServer()
    server.create_table(0, dim=4, optimizer='sgd', lr=0.5)
    server.start()
    try:
        client = EmbeddingClient(endpoints=[server.endpoint])
        ids = np.asarray([1, 7, 9], np.int64)
        rows = client.pull(0, ids)
        assert rows.shape == (3, 4)
        client.push(0, ids, np.ones((3, 4), np.float32))
        rows2 = client.pull(0, ids)
        np.testing.assert_allclose(rows2, rows - 0.5, atol=1e-6)
    finally:
        server.stop()


def test_data_generator_roundtrips_with_dataset(tmp_path):
    """fleet data_generator writes MultiSlot lines the dataset parses back
    (reference data_generator -> data_feed round trip)."""
    from paddle_tpu.distributed.fleet.data_generator import \
        MultiSlotDataGenerator

    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def g():
                a, b, label = line
                yield [('slot0', a), ('slot1', b), ('label', [label])]
            return g

    gen = Gen()
    samples = [([1, 2], [7], 1.0), ([3], [8, 9], 0.0)]
    text = gen.run_from_memory(samples)
    path = tmp_path / 'gen.txt'
    path.write_text(text)

    ds = MultiSlotDataset()
    ds.set_use_var([('slot0', 'int64'), ('slot1', 'int64'),
                    ('label', 'float32')])
    ds.set_filelist([str(path)])
    ds.set_batch_size(2)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 2
    batch = ds.start_channel().get()
    ids0, offs0 = batch['slot0']
    np.testing.assert_array_equal(ids0, [1, 2, 3])
    np.testing.assert_array_equal(offs0, [0, 2, 3])
    np.testing.assert_array_equal(batch['label'], [1.0, 0.0])


def test_pass_cached_embedding_trains_on_device_and_flushes():
    """PSGPU analog (ps_gpu_wrapper BuildPull/EndPass): pass working set
    pulled to HBM, trained as a device Parameter, deltas flushed back."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.ps.heter import PassCachedEmbedding
    from paddle_tpu.framework import functional as func_mod

    server = EmbeddingServer()
    server.create_table(0, dim=4, optimizer='sgd', lr=1.0, init_scale=0.1)
    client = EmbeddingClient(servers=[server])

    paddle.seed(3)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = PassCachedEmbedding(client, 0, 4)
            self.fc = nn.Linear(4, 1)

        def forward(self, slots):
            from paddle_tpu.tensor import math as tmath
            return self.fc(tmath.mean(self.emb(slots), axis=1))

    net = Net()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50, (64, 3)).astype(np.int64)
    y = (ids.min(axis=1, keepdims=True) < 20).astype(np.float32)

    n = net.emb.begin_pass(ids)
    assert n == len(np.unique(ids))
    before = client.pull(0, np.unique(ids)).copy()

    opt = paddle.optimizer.SGD(learning_rate=0.3,
                               parameters=net.parameters())
    step = func_mod.TrainStep(
        net, lambda lg, lb: F.binary_cross_entropy_with_logits(lg, lb),
        opt, donate=False)
    slots = paddle.to_tensor(net.emb.lookup_slots(ids))
    y_t = paddle.to_tensor(y)
    losses = [float(step(slots, y_t).numpy()) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9

    pushed = net.emb.end_pass()
    assert pushed > 0
    after = client.pull(0, np.unique(ids))
    assert not np.allclose(before, after)  # deltas landed host-side
    assert net.emb.table is None           # HBM released

    # out-of-working-set id fails loudly at feed remap
    net.emb.begin_pass(ids)
    import pytest as _pytest
    with _pytest.raises(KeyError, match='working set'):
        net.emb.lookup_slots(np.asarray([999]))


def test_async_executor_facade(tmp_path):
    """Legacy AsyncExecutor API delegates to the modern trainer runtime
    (reference framework/async_executor.cc, deprecated there too)."""
    from paddle_tpu.distributed.ps.trainer import AsyncExecutor
    files = _write_ctr_files(tmp_path, n_files=2)
    server, client = _make_cluster()
    comm = SyncCommunicator(client)
    trainer = DownpourTrainer(client, comm, slots=['slot0', 'slot1'],
                              tables={'slot0': 0, 'slot1': 1},
                              emb_dim=8, hidden=16, lr=0.3, n_threads=1)
    exe = AsyncExecutor()
    losses = exe.run_from_files(
        trainer, files,
        slots=[('slot0', 'int64'), ('slot1', 'int64'),
               ('label', 'float32')],
        batch_size=16, epochs=2, shuffle_seed=0)
    assert len(losses) == 16  # 128 samples / 16 per batch * 2 epochs
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_entry_admission_policies():
    """CountFilterEntry admits a feature only after N sightings;
    ProbabilityEntry samples admission (reference entry_attr)."""
    from paddle_tpu.distributed.ps.embedding_service import (
        EmbeddingTable, CountFilterEntry, ProbabilityEntry)
    t = EmbeddingTable(4, entry=CountFilterEntry(3), init_scale=0.5)
    ids = np.asarray([7], np.int64)
    r1 = t.pull(ids)
    r2 = t.pull(ids)
    np.testing.assert_array_equal(r1, 0.0)  # sightings 1, 2: zeros
    np.testing.assert_array_equal(r2, 0.0)
    assert len(t) == 0
    r3 = t.pull(ids)                        # 3rd sighting: admitted
    assert len(t) == 1 and np.abs(r3).sum() > 0

    t2 = EmbeddingTable(4, entry=ProbabilityEntry(1.0))
    t2.pull(np.asarray([1], np.int64))
    assert len(t2) == 1  # p=1 admits immediately


def test_get_worker_info_in_workers(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, Dataset, get_worker_info

    assert get_worker_info() is None  # main process

    class DS(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            from paddle_tpu.io import get_worker_info as gwi
            info = gwi()
            wid = -1 if info is None else info.id
            return np.asarray([i, wid], np.int64)

    loader = DataLoader(DS(), batch_size=4, num_workers=2, shuffle=False)
    rows = np.concatenate([np.asarray(b) for b in loader])
    # every sample saw a real worker id (0 or 1), never the main proc
    assert set(rows[:, 1].tolist()) <= {0, 1}
