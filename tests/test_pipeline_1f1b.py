"""1F1B pipeline schedule (VERDICT r2 item 5): interleaved fwd/bwd with
O(pp) stash and micro-level loss inside the last stage.

Parity bar: the 1F1B fleet step must produce the same losses as the plain
dp run (reference test style: test_dist_base.py check_with_place loss
deltas). Tied embeddings (wte in pre AND post) are the SharedLayerDesc
grad-correctness case (parallel_layers/pp_layers.py:62).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

# dp x pp meshes take the legacy partial-auto shard_map path
pytestmark = pytest.mark.partial_auto


def _model(seed=0, layers=4, tie=True):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=layers,
                    num_heads=4, max_position_embeddings=32, dropout=0.0,
                    tie_word_embeddings=tie)
    return GPTForCausalLM(cfg)


def _batch(b=8, s=32, vocab=128):
    rng = np.random.RandomState(3)
    ids = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    return ids, lbl


def _strategy(schedule=None, acc=None, **hybrid):
    s = fleet.DistributedStrategy()
    cfg = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
           'sharding_degree': 1, 'sp_degree': 1}
    cfg.update(hybrid)
    s.hybrid_configs = cfg
    if schedule is not None:
        s.pipeline = True
        s.pipeline_configs['schedule_mode'] = schedule
        if acc is not None:
            s.pipeline_configs['accumulate_steps'] = acc
    return s


def _fleet_step(model, strategy):
    fleet.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    return fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=strategy)


@pytest.mark.parametrize('tie', [True, False])
def test_1f1b_matches_dp(tie):
    """pp=2 1F1B (n_micro=4=2*pp by default): same losses as plain dp.
    tie=True exercises the tied-embedding (SharedLayerDesc) grad path —
    wte grads come from rank 0 (embedding) AND the last rank (head)."""
    ids, lbl = _batch()

    ref = _fleet_step(_model(seed=9, tie=tie), _strategy())
    ref_losses = [float(ref(ids, lbl).numpy()) for _ in range(3)]

    s = _strategy(schedule='1F1B', dp_degree=4, pp_degree=2)
    m_pp = _model(seed=9, tie=tie)
    step = _fleet_step(m_pp, s)
    jaxpr = step.trace_jaxpr(ids, lbl)
    assert 'ppermute' in jaxpr
    pp_losses = [float(step(ids, lbl).numpy()) for _ in range(3)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4, atol=2e-5)


def test_1f1b_uneven_layers_matches_dp():
    """n_layers=3 with pp=2 (not divisible): the stack pads with a ghost
    identity layer and still matches the plain dp run — the reference's
    uneven seg_method capability (pp_layers.py:76)."""
    ids, lbl = _batch()
    ref = _fleet_step(_model(seed=21, layers=3), _strategy())
    ref_losses = [float(ref(ids, lbl).numpy()) for _ in range(2)]

    m = _model(seed=21, layers=3)
    step = _fleet_step(m, _strategy(schedule='1F1B', dp_degree=4,
                                    pp_degree=2))
    losses = [float(step(ids, lbl).numpy()) for _ in range(2)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)


def test_1f1b_accumulate_steps_honored():
    """accumulate_steps decouples n_micro from pp (VERDICT: >= 2*pp)."""
    ids, lbl = _batch(b=8)
    s = _strategy(schedule='1F1B', acc=8, dp_degree=4, pp_degree=2)
    model = _model(seed=2)
    step = _fleet_step(model, s)
    assert step._pp_state['n_micro'] == 8
    l0 = float(step(ids, lbl).numpy())
    l1 = float(step(ids, lbl).numpy())
    assert np.isfinite(l0) and l1 < l0


def test_1f1b_pp4_trains():
    ids, lbl = _batch(b=16)
    s = _strategy(schedule='1F1B', dp_degree=2, pp_degree=4)
    model = _model(seed=5)
    step = _fleet_step(model, s)
    losses = [float(step(ids, lbl).numpy()) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_fthenb_mode_still_gpipe():
    ids, lbl = _batch()
    s = _strategy(schedule='F-then-B', dp_degree=4, pp_degree=2)
    model = _model(seed=7)
    step = _fleet_step(model, s)
    assert step._pp_state['schedule'] == 'gpipe'
    assert np.isfinite(float(step(ids, lbl).numpy()))


def test_1f1b_composes_with_mp():
    """1F1B pp2 x mp2 x dp2: TP-sharded params inside the cond-gated
    stages compile and train (the lax.cond branches are consistent
    within each mp group)."""
    ids, lbl = _batch(b=8)
    s = _strategy(schedule='1F1B', dp_degree=2, pp_degree=2, mp_degree=2)
    model = _model(seed=11)
    step = _fleet_step(model, s)
    losses = [float(step(ids, lbl).numpy()) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
