"""End-to-end "book" tests (reference: python/paddle/fluid/tests/book/ —
small real models trained to a loss threshold, doubling as save/load
round-trip tests; test_fit_a_line.py, test_recognize_digits.py,
test_word2vec_book.py).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_fit_a_line(tmp_path):
    """Linear regression on UCIHousing-shaped data to a loss threshold,
    then a jit.save -> predictor round trip (test_fit_a_line.py)."""
    paddle.seed(7)
    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype(np.float32)
    x = rng.randn(128, 13).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(128, 1).astype(np.float32)

    model = nn.Linear(13, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    first = None
    for epoch in range(60):
        loss = F.mse_loss(model(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    final = float(loss.numpy())
    assert final < 0.05 and final < first * 0.05

    # save/load inference round trip
    from paddle_tpu import jit, inference
    path = str(tmp_path / 'fit_a_line')
    model.eval()
    jit.save(model, path)
    pred = inference.create_predictor(inference.Config(path))
    pred.get_input_handle(pred.get_input_names()[0]).copy_from_cpu(x[:4])
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, model(paddle.to_tensor(x[:4])).numpy(),
                               rtol=1e-4, atol=1e-4)


def test_recognize_digits_conv():
    """Small conv net on synthetic digits converges
    (test_recognize_digits.py conv variant)."""
    paddle.seed(1)
    rng = np.random.RandomState(2)
    # separable synthetic "digits": class = brightest quadrant
    n = 128
    imgs = rng.rand(n, 1, 8, 8).astype(np.float32) * 0.1
    labels = rng.randint(0, 4, n)
    for i, c in enumerate(labels):
        r, cc = divmod(int(c), 2)
        imgs[i, 0, r * 4:(r + 1) * 4, cc * 4:(cc + 1) * 4] += 0.9

    model = nn.Sequential(
        nn.Conv2D(1, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(8 * 4 * 4, 4))
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    x_t = paddle.to_tensor(imgs)
    y_t = paddle.to_tensor(labels.astype(np.int64))
    losses = []
    for _ in range(30):
        loss = F.cross_entropy(model(x_t), y_t)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    pred = np.argmax(model(x_t).numpy(), -1)
    acc = (pred == labels).mean()
    assert losses[-1] < losses[0] * 0.3
    assert acc > 0.9, acc


def test_word2vec_book():
    """Tiny skip-gram-style embedding model learns co-occurrence
    (test_word2vec_book.py shape)."""
    paddle.seed(3)
    vocab, dim = 20, 8
    rng = np.random.RandomState(4)
    # pairs: word i co-occurs with i+1 mod vocab
    centers = rng.randint(0, vocab, 256)
    contexts = (centers + 1) % vocab

    class W2V(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim)
            self.out = nn.Linear(dim, vocab)

        def forward(self, ids):
            return self.out(self.emb(ids))

    model = W2V()
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    x_t = paddle.to_tensor(centers.astype(np.int64))
    y_t = paddle.to_tensor(contexts.astype(np.int64))
    losses = []
    for _ in range(40):
        loss = F.cross_entropy(model(x_t), y_t)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < 0.5, losses[-1]
    # the learned next-word distribution picks the right context
    pred = np.argmax(model(paddle.to_tensor(
        np.arange(vocab, dtype=np.int64))).numpy(), -1)
    assert (pred == (np.arange(vocab) + 1) % vocab).mean() > 0.9
