"""Distributed request tracing (paddle_tpu/monitor/tracing.py) and its
three consumers:

  1. cross-process propagation — ResilientChannel injects per-attempt
     trace context, the PS/graph servers continue the trace, and one
     faulted request yields a single causally-linked span tree across
     client retries and the server handler;
  2. serving lifecycle — queued→admit→prefill→decode→retire spans with
     prefix-cache-hit / spec-accept events, TTFT exemplars;
  3. flight recorder + export — bounded ring, exactly-one dump on
     circuit-open / deadline expiry, /debug/traces, Chrome-trace export
     merged by profiler.merge_traces into rank-grouped lanes.

Plus the no-overhead guard: tracing disabled must not measurably slow
the RPC or serving decode hot paths (same discipline as the metrics
registry's disabled-path test in test_monitor.py).
"""
import glob
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.monitor import (MetricRegistry, MetricsServer, to_dict,
                                tracing)
from paddle_tpu.monitor.registry import set_default_registry
from paddle_tpu.monitor.tracing import (NULL_SPAN, TRACE_KEY,
                                        FlightRecorder, Tracer,
                                        set_default_tracer,
                                        spans_to_chrome)
from paddle_tpu.distributed.resilience import (CircuitBreaker,
                                               CircuitOpenError, Deadline,
                                               DeadlineExceeded,
                                               ResilientChannel,
                                               RetryPolicy)
from paddle_tpu.distributed.ps.embedding_service import EmbeddingServer
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine)
from paddle_tpu.testing import chaos
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

FAST = dict(retry_policy=RetryPolicy(max_attempts=4, base_delay=0.02,
                                     max_delay=0.05),
            call_timeout=2.0)


@pytest.fixture(autouse=True)
def no_leaked_faults():
    yield
    assert chaos.active_faults() == 0, 'a chaos injector leaked'


@pytest.fixture
def traced(tmp_path):
    """Fresh registry + tracer (flight dir under tmp_path) installed as
    the process defaults. Swapped in BEFORE anything under test is
    constructed — engines and channels cache the default at creation."""
    reg = MetricRegistry()
    flight = tmp_path / 'flight'
    rec = FlightRecorder(capacity=256, dump_dir=str(flight),
                         cooldown=3600.0, registry=reg)
    tr = Tracer(registry=reg, recorder=rec)
    prev_reg = set_default_registry(reg)
    prev_tr = set_default_tracer(tr)
    yield tr, reg, flight
    set_default_tracer(prev_tr)
    set_default_registry(prev_reg)


@pytest.fixture(scope='module')
def model():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


# -- tracer core -------------------------------------------------------------

def test_span_identity_nesting_and_clock():
    t = [100.0]
    tr = Tracer(registry=MetricRegistry(), clock=lambda: t[0])
    with tr.start_span('outer', tags={'k': 'v'}) as outer:
        assert len(outer.trace_id) == 32 and len(outer.span_id) == 16
        assert outer.parent_id is None
        assert tr.current() is outer
        t[0] = 101.5
        with tr.start_span('inner') as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert inner.start == 101.5
            t[0] = 102.0
        assert tr.current() is outer
    assert tr.current() is None
    assert outer.end == 102.0
    d = [s for s in tr.recorder.spans() if s['name'] == 'outer'][0]
    assert d['tags'] == {'k': 'v'} and d['status'] == 'ok'
    # explicit parent and wire ctx both beat the contextvar
    child = tr.start_span('c', parent=outer)
    assert child.parent_id == outer.span_id
    remote = tr.start_span('r', ctx=outer.ctx())
    assert (remote.trace_id, remote.parent_id) == (outer.trace_id,
                                                   outer.span_id)
    child.finish()
    remote.finish()
    remote.finish()                  # idempotent


def test_span_exit_records_error():
    tr = Tracer(registry=MetricRegistry())
    with pytest.raises(ValueError):
        with tr.start_span('boom'):
            raise ValueError('x')
    d = tr.recorder.spans()[-1]
    assert d['status'] == 'error' and 'ValueError' in d['error']


def test_disabled_tracer_is_null_and_cheap():
    reg = MetricRegistry()
    tr = Tracer(enabled=False, registry=reg)
    sp = tr.start_span('anything')
    assert sp is NULL_SPAN and not sp
    assert sp.ctx() is None
    with sp as s:
        s.set_tag('a', 1).add_event('e').set_error(ValueError())
    sp.finish()
    snap = to_dict(reg)
    assert snap['trace_spans_started_total']['samples'][0]['value'] == 0
    assert snap['trace_spans_finished_total']['samples'][0]['value'] == 0
    assert len(tr.recorder.spans()) == 0
    t0 = time.perf_counter()
    for _ in range(100_000):
        tr.start_span('x')
    assert time.perf_counter() - t0 < 1.0


def test_server_span_always_pops_trace_key():
    tr = Tracer(enabled=False, registry=MetricRegistry())
    msg = {'op': 'pull', TRACE_KEY: {'trace_id': 'aa', 'span_id': 'bb'}}
    assert tr.server_span(msg, 'ps.server') is NULL_SPAN
    assert TRACE_KEY not in msg      # handlers never see the metadata
    tr.enable()
    msg2 = {'op': 'pull', TRACE_KEY: {'trace_id': 'aa', 'span_id': 'bb'}}
    sp = tr.server_span(msg2, 'ps.server')
    assert TRACE_KEY not in msg2
    assert sp.name == 'ps.server.pull'
    assert (sp.trace_id, sp.parent_id) == ('aa', 'bb')
    sp.finish()
    # untraced message on an enabled tracer: no span, nothing popped
    assert tr.server_span({'op': 'pull'}, 'ps.server') is NULL_SPAN


def test_flight_recorder_ring_dump_and_cooldown(tmp_path):
    reg = MetricRegistry()
    t = [0.0]
    rec = FlightRecorder(capacity=3, dump_dir=str(tmp_path),
                         cooldown=10.0, registry=reg, clock=lambda: t[0])
    for i in range(5):
        rec.record({'name': 'n%d' % i})
    assert len(rec) == 3 and rec.dropped == 2
    assert [s['name'] for s in rec.spans()] == ['n2', 'n3', 'n4']
    p1 = rec.maybe_dump('chaos_fault')
    assert p1 and os.path.exists(p1)
    payload = json.load(open(p1))
    assert payload['reason'] == 'chaos_fault'
    assert payload['span_count'] == 3 and payload['dropped'] == 2
    assert rec.maybe_dump('chaos_fault') is None          # cooldown
    assert rec.maybe_dump('circuit_open') is not None     # other reason
    t[0] = 11.0
    assert rec.maybe_dump('chaos_fault') is not None      # window over
    snap = to_dict(reg)
    fam = snap['trace_flight_dumps_total']['samples']
    by_reason = {s['labels']['reason']: s['value'] for s in fam}
    assert by_reason == {'chaos_fault': 2.0, 'circuit_open': 1.0}
    # no dump_dir -> inspection only
    rec2 = FlightRecorder(capacity=3, registry=reg)
    assert rec2.dump_dir is None or 'PADDLE_TPU_FLIGHT_DIR' in os.environ
    rec2.dump_dir = None
    assert rec2.maybe_dump('chaos_fault') is None
    with pytest.raises(ValueError):
        rec2.dump()
    rec.clear()
    assert len(rec) == 0


# -- cross-process propagation under chaos -----------------------------------

@pytest.mark.chaos
def test_one_trace_spans_client_retries_and_server(traced):
    """N injected faults -> exactly N error attempt spans, all parented
    on one rpc.call, the server handler span parented on the surviving
    attempt, every span sharing one trace_id."""
    tr, reg, flight = traced
    srv = EmbeddingServer()
    srv.create_table(0, dim=4, seed=0)
    srv.start()
    ch = ResilientChannel(srv.endpoint, **FAST)
    try:
        with chaos.drop_connections(point='send', times=2) as fault:
            out = ch.call({'op': 'pull', 'table': 0,
                           'ids': np.array([1, 2], np.int64)})
        assert fault.fired == 2
        assert np.asarray(out).shape == (2, 4)
        # the handler finishes its span after replying; give it a beat
        deadline = time.time() + 2.0
        while time.time() < deadline:
            if any(s['name'] == 'ps.server.pull'
                   for s in tr.recorder.spans()):
                break
            time.sleep(0.01)
    finally:
        ch.close()
        srv.stop()
    spans = tr.recorder.spans()
    calls = [s for s in spans if s['name'] == 'rpc.call']
    attempts = [s for s in spans if s['name'] == 'rpc.attempt']
    servers = [s for s in spans if s['name'] == 'ps.server.pull']
    assert len(calls) == 1 and len(attempts) == 3 and len(servers) == 1
    call = calls[0]
    assert call['tags']['endpoint'] == srv.endpoint
    # single trace across both processes' spans
    assert {s['trace_id'] for s in spans} == {call['trace_id']}
    assert all(a['parent_id'] == call['span_id'] for a in attempts)
    failed = [a for a in attempts if a['status'] == 'error']
    assert len(failed) == fault.fired == 2
    ok = [a for a in attempts if a['status'] == 'ok']
    assert len(ok) == 1
    assert servers[0]['parent_id'] == ok[0]['span_id']
    assert ok[0]['tags']['retries'] == 2
    # chaos annotated the in-flight call span, once per fault
    ev = [e for e in call['events'] if e['name'] == 'chaos.fault']
    assert len(ev) == 2
    assert all(e['args']['point'] == 'send' for e in ev)
    # backoff waits were recorded on the call span too
    assert sum(1 for e in call['events'] if e['name'] == 'backoff') == 2
    # and each fault offered the recorder a dump (one survives cooldown)
    assert len(glob.glob(str(flight / 'flight_chaos_fault_*.json'))) == 1


@pytest.mark.chaos
def test_circuit_open_dumps_exactly_once(traced):
    tr, reg, flight = traced
    ch = ResilientChannel('127.0.0.1:1',
                          retry_policy=RetryPolicy(max_attempts=6,
                                                   base_delay=0.001,
                                                   max_delay=0.002),
                          breaker=CircuitBreaker(failure_threshold=3,
                                                 reset_timeout=60.0))
    with pytest.raises(CircuitOpenError):
        ch.call({'op': 'stats'})
    dumps = glob.glob(str(flight / 'flight_circuit_open_*.json'))
    assert len(dumps) == 1
    payload = json.load(open(dumps[0]))
    assert payload['reason'] == 'circuit_open'
    # the failing attempt made it into the ring BEFORE the dump
    att = [s for s in payload['spans'] if s['name'] == 'rpc.attempt']
    assert att and all(s['status'] == 'error' for s in att)
    assert att[-1]['tags']['retries'] == 2
    # a second (fast-failed) call must not dump again
    with pytest.raises(CircuitOpenError):
        ch.call({'op': 'stats'})
    assert len(glob.glob(str(flight / 'flight_circuit_open_*.json'))) == 1
    # both call spans carry the fast-fail tag: the first trips the
    # breaker on attempt 3 and fast-fails attempt 4; the second never
    # gets an attempt at all
    fast = [s for s in tr.recorder.spans() if s['name'] == 'rpc.call'
            and s['tags'].get('circuit_open_fast_fail')]
    assert len(fast) == 2
    ch.close()


@pytest.mark.chaos
def test_deadline_expiry_dumps(traced):
    tr, reg, flight = traced
    ch = ResilientChannel('127.0.0.1:1', **FAST)
    with pytest.raises(DeadlineExceeded):
        ch.call({'op': 'stats'}, deadline=Deadline(0.0))
    dumps = glob.glob(str(flight / 'flight_deadline_expired_*.json'))
    assert len(dumps) == 1
    call = [s for s in tr.recorder.spans() if s['name'] == 'rpc.call'][-1]
    assert call['tags']['deadline_expired'] is True
    ch.close()


def test_disabled_tracing_keeps_call_payload_clean(traced):
    """Tracing off: no TRACE_KEY on the wire, no spans recorded."""
    tr, reg, flight = traced
    tr.disable()
    srv = EmbeddingServer()
    srv.create_table(0, dim=4, seed=0)
    srv.start()
    ch = ResilientChannel(srv.endpoint, **FAST)
    try:
        out = ch.call({'op': 'pull', 'table': 0,
                       'ids': np.array([3], np.int64)})
        assert np.asarray(out).shape == (1, 4)
    finally:
        ch.close()
        srv.stop()
    assert tr.recorder.spans() == []


# -- serving lifecycle --------------------------------------------------------

def test_serving_lifecycle_spans_and_exemplars(model, traced):
    tr, reg, flight = traced
    eng = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    prompts = [[int(t) for t in np.random.RandomState(5).randint(0, 211, n)]
               for n in (12, 3)]
    eng.generate(prompts, max_new_tokens=6)
    spans = tr.recorder.spans()
    reqs = [s for s in spans if s['name'] == 'serving.request']
    assert len(reqs) == 2
    for r in reqs:
        assert r['parent_id'] is None
        names = [e['name'] for e in r['events']]
        assert names[0] == 'queued'
        assert 'admitted' in names and names[-1] == 'retired'
        assert r['tags']['tokens'] == 6
        assert r['tags']['prompt_len'] in (12, 3)
    by_span = {r['span_id']: r['trace_id'] for r in reqs}
    prefills = [s for s in spans if s['name'] == 'serving.prefill']
    decodes = [s for s in spans if s['name'] == 'serving.decode']
    assert len(prefills) == 2 and len(decodes) == 2
    for ph in prefills + decodes:
        assert ph['parent_id'] in by_span
        assert ph['trace_id'] == by_span[ph['parent_id']]
    # the 12-token prompt prefilled in two chunks of <= 8
    chunks = max(len([e for e in p['events']
                      if e['name'] == 'prefill_chunk']) for p in prefills)
    assert chunks == 2
    bursts = [s for s in spans if s['name'] == 'serving.decode_burst']
    assert bursts and all(s['tags']['block'] == 4 for s in bursts)
    # TTFT observations carry trace_id exemplars linking back to requests
    snap = to_dict(reg, buckets=True)
    ttft = snap['serving_ttft_seconds']['samples'][0]
    exemplars = ttft.get('exemplars') or {}
    assert exemplars
    traces = {r['trace_id'] for r in reqs}
    assert {e['trace_id'] for e in exemplars.values()} <= traces
    gap = snap['serving_inter_token_seconds']['samples'][0]
    assert gap.get('exemplars')
    n_ex = snap['trace_exemplars_total']['samples'][0]['value']
    assert n_ex > 0


def test_paged_prefix_hit_and_spec_accept_events(model, traced):
    tr, reg, flight = traced
    rng = np.random.RandomState(11)
    system = [int(t) for t in rng.randint(0, 211, 16)]
    prompts = [system + [int(t) for t in rng.randint(0, 211, 3)]
               for _ in range(4)]
    eng = PagedContinuousBatchingEngine(model, num_seqs=2, max_len=64,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=4, spec_k=2)
    eng.generate(prompts, max_new_tokens=6)
    assert eng.metrics.report()['prefix_hits'] > 0
    reqs = [s for s in tr.recorder.spans()
            if s['name'] == 'serving.request']
    assert len(reqs) == 4
    events = [e for r in reqs for e in r['events']]
    hits = [e for e in events if e['name'] == 'prefix_cache_hit']
    assert hits and all(e['args']['tokens'] > 0 for e in hits)
    accepts = [e for e in events if e['name'] == 'spec_accept']
    assert accepts and all(e['args']['proposed'] == 2 for e in accepts)


# -- /debug/traces + export ---------------------------------------------------

def test_debug_traces_endpoint_and_head(traced):
    tr, reg, flight = traced
    with tr.start_span('unit.request', tags={'k': 'v'}):
        pass
    with MetricsServer(registry=reg, tracer=tr) as srv:
        body = json.loads(urllib.request.urlopen(
            srv.url + '/debug/traces', timeout=5).read().decode())
        assert body['enabled'] is True
        assert body['capacity'] == 256 and body['dropped'] == 0
        assert [s['name'] for s in body['spans']] == ['unit.request']
        chrome = json.loads(urllib.request.urlopen(
            srv.url + '/debug/traces?format=chrome',
            timeout=5).read().decode())
        names = [e['name'] for e in chrome['traceEvents']]
        assert 'process_name' in names and 'unit.request' in names
        # HEAD answers every route with real headers and an empty body
        for path in ('/healthz', '/metrics', '/debug/traces'):
            req = urllib.request.Request(srv.url + path, method='HEAD')
            resp = urllib.request.urlopen(req, timeout=5)
            assert resp.status == 200
            assert int(resp.headers['Content-Length']) > 0
            assert resp.read() == b''
        req = urllib.request.Request(srv.url + '/nope', method='HEAD')
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req, timeout=5)


def test_no_tracer_endpoint_404(traced):
    tr, reg, flight = traced
    srv = MetricsServer(registry=reg, tracer=tr)
    srv.tracer = None
    with srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + '/debug/traces', timeout=5)
        assert ei.value.code == 404


def test_chrome_export_merges_with_rank_traces(traced, tmp_path):
    """Acceptance: a host-span export dir + a per-rank device-trace dir
    merge into ONE valid Chrome-trace JSON with rank-grouped lanes."""
    tr, reg, flight = traced
    with tr.start_span('host.step', tags={'step': 1}) as sp:
        sp.add_event('mark', x=1)
    host_dir = tmp_path / 'host'
    tr.recorder.export_chrome(str(host_dir / 'host.trace.json'),
                              process_name='trainer host')
    rank_dir = tmp_path / 'rank1'
    os.makedirs(str(rank_dir))
    with open(str(rank_dir / 'device.trace.json'), 'w') as fh:
        json.dump({'traceEvents': [
            {'ph': 'M', 'name': 'process_name', 'pid': 7,
             'args': {'name': 'tpu worker'}},
            {'ph': 'X', 'name': 'xla_op', 'pid': 7, 'tid': 1,
             'ts': 10.0, 'dur': 5.0}]}, fh)
    out = str(tmp_path / 'merged.json')
    profiler.merge_traces([str(host_dir), str(rank_dir)], out)
    merged = json.load(open(out))
    assert merged['metadata']['merged_ranks'] == 2
    evs = merged['traceEvents']
    pnames = [e['args']['name'] for e in evs
              if e.get('ph') == 'M' and e.get('name') == 'process_name']
    assert any(n.startswith('rank 0:') for n in pnames)
    assert any(n == 'rank 1: tpu worker' for n in pnames)
    names = [e.get('name') for e in evs]
    assert 'host.step' in names and 'xla_op' in names and 'mark' in names
    # rank lanes are disjoint pid ranges
    host_pid = [e['pid'] for e in evs if e.get('name') == 'host.step'][0]
    dev_pid = [e['pid'] for e in evs if e.get('name') == 'xla_op'][0]
    assert host_pid < (1 << 20) <= dev_pid


def test_spans_to_chrome_shapes():
    tr = Tracer(registry=MetricRegistry(), clock=iter(
        [1.0, 1.25, 1.5]).__next__)
    with tr.start_span('a', tags={'q': 7}) as sp:
        sp.add_event('e')
    doc = spans_to_chrome(tr.recorder.spans(), pid=42)
    xs = [e for e in doc['traceEvents'] if e['ph'] == 'X']
    assert xs[0]['ts'] == 1.0e6 and xs[0]['dur'] == 0.5e6
    assert xs[0]['pid'] == 42 and xs[0]['args']['q'] == 7
    inst = [e for e in doc['traceEvents'] if e['ph'] == 'i']
    assert inst[0]['name'] == 'e' and inst[0]['ts'] == 1.25e6


# -- profiler fixes -----------------------------------------------------------

def test_profiler_stop_without_start_is_safe():
    p = profiler.Profiler(timer_only=False)
    p.stop()                                    # never started
    p.stop()                                    # and again
    profiler.stop_profiler()                    # module-level too
    profiler.stop_profiler()


def test_profiler_failed_start_leaves_no_stale_state(monkeypatch,
                                                     tmp_path):
    def boom(*a, **k):
        raise RuntimeError('trace backend unavailable')
    monkeypatch.setattr(profiler.jax.profiler, 'start_trace', boom)
    p = profiler.Profiler(log_dir=str(tmp_path))
    with pytest.raises(RuntimeError):
        p.start()
    p.stop()                                    # must not raise
    with pytest.raises(RuntimeError):
        profiler.start_profiler(log_dir=str(tmp_path))
    assert profiler._active_dir[0] is None      # no stale active dir
    profiler.stop_profiler()                    # paired stop is a no-op


def test_record_event_emits_host_span(traced):
    tr, reg, flight = traced
    with profiler.RecordEvent('fused_step'):
        pass
    ev = profiler.RecordEvent('begin_end')
    ev.begin()
    ev.end()
    names = [s['name'] for s in tr.recorder.spans()]
    assert names == ['fused_step', 'begin_end']


# -- overhead guards ----------------------------------------------------------

def test_disabled_tracing_adds_no_measurable_channel_overhead(traced):
    """Same shape as the registry's disabled-overhead guard: with the
    tracer off a loopback call does strictly less work, so its trimmed
    mean must not exceed the enabled mean + generous slack."""
    tr, reg, flight = traced
    srv = EmbeddingServer()
    srv.create_table(0, dim=4, seed=0)
    srv.start()
    ch = ResilientChannel(srv.endpoint)
    msg = {'op': 'dims', 'table_id': 0}

    def mean_call_s(n=60):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            ch.call(msg)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return sum(ts[:n // 2]) / (n // 2)

    try:
        assert tr.enabled
        mean_call_s(10)                          # warm both paths
        enabled = mean_call_s()
        tr.disable()
        try:
            disabled = mean_call_s()
        finally:
            tr.enable()
    finally:
        ch.close()
        srv.stop()
    assert disabled <= enabled + 2e-3, (disabled, enabled)


def test_disabled_tracing_adds_no_measurable_decode_overhead(model,
                                                             traced):
    """Drive the same engine's decode hot loop with tracing on, then
    off: the disabled path must not be slower beyond scheduling noise
    (a decode step costs milliseconds; the guard is absolute)."""
    tr, reg, flight = traced
    eng = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    prompt = [1, 2, 3]

    def run_one():
        eng.add_request(prompt, max_new_tokens=16)
        t0 = time.perf_counter()
        eng.run()
        return time.perf_counter() - t0

    run_one()                                    # compile both programs
    enabled = min(run_one() for _ in range(3))
    tr.disable()
    try:
        disabled = min(run_one() for _ in range(3))
    finally:
        tr.enable()
    # generous absolute slack: CPU jit dispatch jitter dwarfs span cost
    assert disabled <= enabled * 1.5 + 0.05, (disabled, enabled)
