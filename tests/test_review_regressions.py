"""Regressions for code-review findings (round-1 review)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.functional import TrainStep


def test_trainstep_honors_weight_decay_and_clip():
    """TrainStep must apply AdamW decoupled decay + grad clip exactly like
    eager Optimizer.step."""
    def build():
        paddle.seed(5)
        m = nn.Linear(4, 4)
        o = paddle.optimizer.AdamW(
            learning_rate=1e-2, parameters=m.parameters(), weight_decay=0.1,
            grad_clip=nn.ClipGradByGlobalNorm(0.5))
        return m, o

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32) * 10)
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    loss_fn = nn.MSELoss()

    m1, o1 = build()
    for _ in range(3):
        l1 = loss_fn(m1(x), y)
        l1.backward()
        o1.step()
        o1.clear_grad()

    m2, o2 = build()
    step = TrainStep(m2, loss_fn, o2)
    for _ in range(3):
        step(x, y)

    for (n1, p1), (_, p2) in zip(m1.named_parameters(), m2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5,
                                   err_msg=n1)


def test_batchnorm_eager_grad_correct():
    """BN backward must differentiate through batch statistics."""
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    xv = rng.standard_normal((6, 3, 4, 4)).astype(np.float32)

    bn = nn.BatchNorm2D(3)
    bn.train()
    x = paddle.to_tensor(xv, stop_gradient=False)
    out = bn(x)
    out.sum().backward()

    def ref(a):
        m = jnp.mean(a, axis=(0, 2, 3))
        v = jnp.var(a, axis=(0, 2, 3))
        xhat = (a - m.reshape(1, -1, 1, 1)) * jax.lax.rsqrt(
            v.reshape(1, -1, 1, 1) + 1e-5)
        return jnp.sum(xhat)  # weight=1, bias=0 at init
    g = jax.grad(ref)(jnp.asarray(xv))
    np.testing.assert_allclose(x.grad.numpy(), np.asarray(g), atol=1e-4)


def test_layernorm_bias_only():
    ln = nn.LayerNorm(4, weight_attr=False)
    assert ln.weight is None and ln.bias is not None
    ln.bias.set_value(np.full(4, 0.5, np.float32))
    x = paddle.randn([2, 4])
    out = ln(x).numpy()
    xn = x.numpy()
    ref = (xn - xn.mean(-1, keepdims=True)) / \
        np.sqrt(xn.var(-1, keepdims=True) + 1e-5) + 0.5
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_gradscaler_explicit_unscale_then_step():
    from paddle_tpu.amp import GradScaler
    w = paddle.framework.Parameter(np.ones(2, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = GradScaler(init_loss_scaling=1024.0)
    scaler.scale((w * 2).sum()).backward()
    scaler.unscale_(opt)
    grad_after_unscale = w.grad.numpy().copy()
    scaler.step(opt)  # must NOT unscale again
    scaler.update()
    np.testing.assert_allclose(grad_after_unscale, [2., 2.], rtol=1e-6)
    np.testing.assert_allclose(w.numpy(), [0.8, 0.8], rtol=1e-6)


def test_nonleaf_hook_transforms_gradient():
    x = paddle.to_tensor([1., 2.], stop_gradient=False)
    y = x * 2
    y.register_hook(lambda g: g * 10)
    (y * 3).sum().backward()
    # dL/dy = 3, hook makes it 30, dL/dx = 60
    np.testing.assert_allclose(x.grad.numpy(), [60., 60.])


def test_hook_id_not_reused_after_remove():
    x = paddle.to_tensor([1.], stop_gradient=False)
    calls = []
    h0 = x.register_hook(lambda g: calls.append('a'))
    h1 = x.register_hook(lambda g: calls.append('b'))
    h0.remove()
    x.register_hook(lambda g: calls.append('c'))
    (x * 1.0).sum().backward()
    assert sorted(calls) == ['b', 'c']


def test_create_graph_raises():
    x = paddle.to_tensor([2.], stop_gradient=False)
    y = (x ** 3).sum()
    with pytest.raises(NotImplementedError):
        paddle.grad(y, x, create_graph=True)


def test_double_backward_error_message():
    x = paddle.to_tensor([1.], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError, match='second time'):
        y.backward()


def test_cummax_values_and_indices():
    x = paddle.to_tensor([[1., 3., 2.], [4., 0., 5.]])
    vals, idx = paddle.cummax(x, axis=1)
    np.testing.assert_allclose(vals.numpy(), [[1., 3., 3.], [4., 4., 5.]])
    np.testing.assert_allclose(idx.numpy(), [[0, 1, 1], [0, 0, 2]])
