"""Cluster-level trace merge (VERDICT r3 item 8): per-rank profiler dirs
from a REAL 2-process run merge into one chrome-tracing timeline with
per-rank lanes — the tools/CrossStackProfiler capability."""
import importlib
import json
import os

import numpy as np
import pytest

spawn_mod = importlib.import_module('paddle_tpu.distributed.spawn')


def _profiled_worker():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import paddle_tpu.profiler as profiler

    # the spawn bootstrap seats the per-rank dir in this env var
    assert os.environ['PADDLE_TRAINER_TRACE_DIR'].endswith(
        'rank_' + os.environ['PADDLE_TRAINER_ID'])
    prof = profiler.Profiler()
    with prof:
        with profiler.RecordEvent('worker_compute'):
            x = jnp.ones((64, 64))
            (x @ x).block_until_ready()


@pytest.mark.slow
def test_two_proc_traces_merge(tmp_path):
    base = tmp_path / 'traces'
    os.environ['PADDLE_TRAINER_TRACE_DIR'] = str(base)
    try:
        spawn_mod.spawn(_profiled_worker, nprocs=2)
    finally:
        del os.environ['PADDLE_TRAINER_TRACE_DIR']

    import paddle_tpu.profiler as profiler
    rank_dirs = [str(base / 'rank_0'), str(base / 'rank_1')]
    for d in rank_dirs:
        assert profiler.load_profiler_result(d), 'no trace artifacts in %s' % d

    out = str(tmp_path / 'merged.json')
    profiler.merge_traces(rank_dirs, out)
    with open(out) as f:
        doc = json.load(f)
    evs = doc['traceEvents']
    assert doc['metadata']['merged_ranks'] == 2
    assert len(evs) > 0
    labels = {e['args']['name'] for e in evs
              if e.get('ph') == 'M' and e.get('name') == 'process_name'}
    assert any(l.startswith('rank 0') for l in labels)
    assert any(l.startswith('rank 1') for l in labels)
    # rank lanes are disjoint pid ranges
    pids0 = {e['pid'] for e in evs if e.get('pid', 0) < (1 << 20)}
    pids1 = {e['pid'] for e in evs if e.get('pid', 0) >= (1 << 20)}
    assert pids0 and pids1
