"""End-to-end GNN training over the distributed graph engine: two-block
community graph in the service, GraphSAGE sampling + aggregation on
device, node classification accuracy as evidence the whole pipeline
(store -> sampler -> padded batch -> jittable layer -> autograd) works.
Reference pipeline: common_graph_table.cc + graph_py_service.cc feeding
PGL-style trainers."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.graph_learning import (
    neighbor_sample, sample_and_gather, GraphSageLayer)


def _community_graph(client, n_per=24, dim=4, seed=0):
    """Two dense communities with sparse cross links; features are a
    noisy community indicator only in the FIRST coordinate pair."""
    rng = np.random.RandomState(seed)
    n = 2 * n_per
    src, dst = [], []
    for c in (0, 1):
        base = c * n_per
        for i in range(n_per):
            nbrs = rng.choice(n_per, 4, replace=False)
            for j in nbrs:
                src.append(base + i)
                dst.append(base + int(j))
    for _ in range(4):  # weak cross-community noise
        src.append(int(rng.randint(0, n_per)))
        dst.append(int(n_per + rng.randint(0, n_per)))
    client.add_edges('default', np.asarray(src), np.asarray(dst))
    feats = rng.randn(n, dim).astype(np.float32) * 0.5
    labels = np.repeat([0, 1], n_per)
    feats[:, 0] += labels * 1.0 - 0.5
    client.set_node_feat('default', np.arange(n), feats)
    return n, labels


def test_graphsage_trains_on_engine_samples():
    from paddle_tpu.distributed.graph_service import GraphPyService
    paddle.seed(0)
    svc = GraphPyService()
    client = svc.set_up(num_servers=2)
    try:
        dim, fanout = 4, 6
        n, labels = _community_graph(client, dim=dim)

        sage1 = GraphSageLayer(dim, 16)
        head = nn.Linear(16, 2)
        params = sage1.parameters() + head.parameters()
        opt = paddle.optimizer.Adam(learning_rate=5e-2, parameters=params)
        ce = nn.CrossEntropyLoss()

        ids = np.arange(n)
        first = last = None
        for epoch in range(30):
            self_f, (hop1_f,) = sample_and_gather(client, 'default', ids,
                                                  [fanout], dim)
            h = sage1(paddle.to_tensor(self_f), paddle.to_tensor(hop1_f))
            logits = head(h)
            loss = ce(logits, paddle.to_tensor(labels.astype(np.int64)))
            loss.backward()
            opt.step()
            opt.clear_grad()
            v = float(loss.numpy())
            first = v if first is None else first
            last = v
        assert last < first * 0.3, (first, last)

        self_f, (hop1_f,) = sample_and_gather(client, 'default', ids,
                                              [fanout], dim)
        pred = np.argmax(head(sage1(paddle.to_tensor(self_f),
                                    paddle.to_tensor(hop1_f))).numpy(), -1)
        acc = (pred == labels).mean()
        assert acc > 0.9, acc
    finally:
        svc.stop()


def test_neighbor_sample_self_fallback():
    from paddle_tpu.distributed.graph_service import GraphPyService
    svc = GraphPyService()
    client = svc.set_up(num_servers=1)
    try:
        client.add_edges('default', np.asarray([0]), np.asarray([1]))
        # node 5 is isolated: all fanout slots fall back to the node itself
        out = neighbor_sample(client, 'default', np.asarray([0, 5]), 3)
        assert out.shape == (2, 3)
        assert (out[0] == 1).all()
        assert (out[1] == 5).all()
    finally:
        svc.stop()
