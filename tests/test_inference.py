"""Inference engine tests (reference pattern:
inference/tests/api/analyzer_*_tester.cc — save a model, load through the
predictor, compare vs native forward)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.vision.models import LeNet


def test_predictor_roundtrip(tmp_path):
    paddle.seed(0)
    model = LeNet()
    model.eval()
    path = str(tmp_path / 'lenet')
    from paddle_tpu.static import InputSpec
    paddle.jit.save(model, path, input_spec=[InputSpec([1, 1, 28, 28])])

    from paddle_tpu import inference
    config = inference.Config(path)
    config.enable_memory_optim()
    config.switch_ir_optim(True)
    predictor = inference.create_predictor(config)

    x = np.random.RandomState(0).standard_normal((2, 1, 28, 28)).astype(
        np.float32)
    # zero-copy style API
    names = predictor.get_input_names()
    h = predictor.get_input_handle(names[0])
    h.copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()

    ref = model(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    # list API + signature-cache second shape
    out2 = predictor.run([x[:1]])[0]
    np.testing.assert_allclose(out2, ref[:1], rtol=1e-4, atol=1e-5)


def test_predictor_bf16_precision(tmp_path):
    paddle.seed(1)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model.eval()
    path = str(tmp_path / 'mlp')
    paddle.jit.save(model, path)

    from paddle_tpu import inference
    config = inference.Config(path)
    config.enable_tensorrt_engine(
        precision_mode=inference.PrecisionType.Bfloat16)
    predictor = inference.create_predictor(config)
    x = np.random.RandomState(1).standard_normal((4, 8)).astype(np.float32)
    out = predictor.run([x])[0]
    ref = model(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out.astype(np.float32), ref, atol=0.1)


def test_predictor_named_inputs_and_validation(tmp_path):
    # inputs resolved by the SAVED spec names; unknown names rejected at
    # copy_from_cpu time; run() fails loudly on missing inputs
    import pytest
    paddle.seed(2)
    model = nn.Sequential(nn.Linear(8, 4))
    model.eval()
    path = str(tmp_path / 'named')
    from paddle_tpu.static import InputSpec
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([2, 8], name='features')])

    from paddle_tpu import inference
    predictor = inference.create_predictor(inference.Config(path))
    assert predictor.get_input_names() == ['features']
    with pytest.raises(ValueError):
        predictor.get_input_handle('bogus').copy_from_cpu(np.zeros((2, 8)))
    with pytest.raises(ValueError):
        predictor.run()
    x = np.random.RandomState(2).standard_normal((2, 8)).astype(np.float32)
    predictor.get_input_handle('features').copy_from_cpu(x)
    predictor.run()
    out = predictor.get_output_handle('output_0').copy_to_cpu()
    np.testing.assert_allclose(out, model(paddle.to_tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_jit_artifact_version_gate(tmp_path):
    """Saved programs carry format/framework versions; a newer-major
    artifact refuses to load (reference op_version_registry compat)."""
    import pickle
    import paddle_tpu as paddle
    from paddle_tpu import jit
    import paddle_tpu.nn as nn

    model = nn.Linear(4, 2)
    path = str(tmp_path / 'm')
    jit.save(model, path)
    with open(path + '.pdmodel', 'rb') as f:
        payload = pickle.load(f)
    assert payload['meta']['format_version'] == jit._FORMAT_VERSION
    assert payload['meta']['framework_version'] == paddle.__version__

    payload['meta']['format_version'] = (jit._FORMAT_VERSION[0] + 1, 0)
    with open(path + '.pdmodel', 'wb') as f:
        pickle.dump(payload, f)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match='NEWER framework'):
        jit.load(path)
