"""Secondary benchmark harness for the BASELINE.md tracked configs that
bench.py's single-line contract does not cover:

  config 2 — ResNet-50 train throughput (images/sec), @to_static -> XLA
  config 4 — YOLO-family inference latency through AnalysisPredictor

Prints one JSON line per config. Safe anywhere: CPU runs are tagged
degraded (tiny shapes); TPU runs use the real config. Not invoked by the
driver — evidence harness for manual runs (python bench_extra.py).
"""
import json
import time

import numpy as np


def _platform():
    import jax
    return jax.devices()[0].platform


def bench_resnet(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50, resnet18
    from paddle_tpu.framework import functional as func_mod

    paddle.seed(0)
    if on_tpu:
        model, batch, steps, size = resnet50(), 64, 20, 224
        model.bfloat16()
    else:
        model, batch, steps, size = resnet18(), 2, 2, 32
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    ce = paddle.nn.CrossEntropyLoss()
    step = func_mod.TrainStep(model, lambda lo, la: ce(lo, la), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, size, size).astype(np.float32))
    if on_tpu:
        # params are bf16 — conv requires matching operand dtypes
        x = x.astype('bfloat16')
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
    step(x, y).numpy()                      # compile
    warm = 10 if on_tpu else 1
    for _ in range(warm):
        loss = step(x, y)
    _ = loss.numpy()
    t0 = time.time()
    for _ in range(steps):
        loss = step(x, y)
    _ = loss.numpy()
    dt = time.time() - t0
    return {'metric': 'resnet_train_images_per_sec',
            'value': round(batch * steps / dt, 2), 'unit': 'images/sec',
            'batch': batch, 'image_size': size,
            'model': type(model).__name__,
            'degraded': not on_tpu}


def bench_yolo_infer(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.vision.models.yolo import ppyolov2
    paddle.seed(0)
    size = 320 if on_tpu else 64
    model = ppyolov2(num_classes=80)
    model.eval()
    import jax
    from paddle_tpu.framework.functional import (extract_params,
                                                 extract_buffers,
                                                 functional_call)
    params = extract_params(model)
    buffers = extract_buffers(model)

    def fwd(p, b, img):
        out, _ = functional_call(model, p, b, (paddle.Tensor(img),),
                                 training=False)
        return out
    jfwd = jax.jit(fwd)
    img = np.random.RandomState(0).rand(1, 3, size, size).astype(np.float32)
    out = jfwd(params, buffers, img)
    jax.block_until_ready(out)
    n = 20 if on_tpu else 2
    t0 = time.time()
    for _ in range(n):
        out = jfwd(params, buffers, img)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0])
    dt = (time.time() - t0) / n
    return {'metric': 'yolo_infer_latency_ms', 'value': round(dt * 1e3, 2),
            'unit': 'ms', 'image_size': size, 'degraded': not on_tpu}


def bench_gpt_decode(on_tpu):
    """Autoregressive decode throughput (tokens/sec) through the jitted
    static-cache step (GPTForCausalLM.generate)."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        batch, prompt_len, new_tokens = 8, 128, 128
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        dropout=0.0)
        batch, prompt_len, new_tokens = 2, 8, 16
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    prompt = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, prompt_len)).astype(
            np.int32))
    out = model.generate(prompt, max_new_tokens=new_tokens)   # compile
    _ = out.numpy()
    t0 = time.time()
    out = model.generate(prompt, max_new_tokens=new_tokens)
    _ = out.numpy()
    dt = time.time() - t0
    return {'metric': 'gpt_decode_tokens_per_sec',
            'value': round(batch * new_tokens / dt, 2),
            'unit': 'tokens/sec', 'batch': batch,
            'prompt_len': prompt_len, 'new_tokens': new_tokens,
            'degraded': not on_tpu}


def main():
    on_tpu = _platform() == 'tpu'
    for fn in (bench_resnet, bench_yolo_infer, bench_gpt_decode):
        try:
            print(json.dumps(fn(on_tpu)))
        except Exception as e:  # never die half-way
            print(json.dumps({'metric': fn.__name__, 'error': repr(e)[:300]}))


if __name__ == '__main__':
    main()
