"""Secondary benchmark harness for the BASELINE.md tracked configs that
bench.py's single-line contract does not cover:

  config 2 — ResNet-50 train throughput (images/sec), @to_static -> XLA
  config 4 — YOLO-family inference latency/QPS through AnalysisPredictor
  (plus)   — GPT decode tokens/sec through the single-dispatch scan path

Prints one JSON line per config. Safe anywhere: CPU runs are tagged
degraded (tiny shapes); TPU runs use the real config. Not invoked by the
driver — evidence harness for the warmer and manual runs
(python bench_extra.py).
"""
import json
import statistics
import time

import numpy as np


def _platform():
    import os
    import bench
    import jax
    # same override bench.py children honor (one name: bench's constant):
    # lets drills/CI force CPU without touching the possibly wedged relay
    forced = os.environ.get(bench._PLATFORM_ENV)
    if forced:
        jax.config.update('jax_platforms', forced)
    return jax.devices()[0].platform


def _enable_cache():
    # same repo-local persistent XLA cache bench.py children use (one
    # config path: framework/compile_cache.py): every executable
    # compiled in an up-window is a warm artifact later
    import bench
    bench._enable_persistent_cache()


def bench_resnet(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50, resnet18
    from paddle_tpu.framework import functional as func_mod

    paddle.seed(0)
    if on_tpu:
        model, batch, steps, size = resnet50(), 64, 20, 224
        model.bfloat16()
    else:
        model, batch, steps, size = resnet18(), 2, 2, 32
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    ce = paddle.nn.CrossEntropyLoss()
    step = func_mod.TrainStep(model, lambda lo, la: ce(lo, la), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, size, size).astype(np.float32))
    if on_tpu:
        # params are bf16 — conv requires matching operand dtypes
        x = x.astype('bfloat16')
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
    step(x, y).numpy()                      # compile
    warm = 10 if on_tpu else 1
    for _ in range(warm):
        loss = step(x, y)
    _ = loss.numpy()
    t0 = time.time()
    for _ in range(steps):
        loss = step(x, y)
    _ = loss.numpy()
    dt = time.time() - t0
    return {'metric': 'resnet_train_images_per_sec',
            'value': round(batch * steps / dt, 2), 'unit': 'images/sec',
            'batch': batch, 'image_size': size,
            'model': type(model).__name__,
            'degraded': not on_tpu}


def bench_yolo_infer(on_tpu):
    """Config 4: PP-YOLOv2 inference, batch 1 AND 8, median-of-repeats.

    Round-4 single-run captures varied 1.5x (205.9 vs 140.2 ms same
    config) — each batch size now reports the median of `reps` timed
    passes plus the spread, so a noisy relay shows up as spread instead
    of silently biasing the number. Budget (docs/PERF_NOTES_r5.md): the
    v5e roofline for this graph is ~10 ms/img; <50 ms/img batch-1 is the
    pass bar, QPS scales with batch.
    """
    import paddle_tpu as paddle
    from paddle_tpu.vision.models.yolo import ppyolov2
    paddle.seed(0)
    size = 320 if on_tpu else 64
    model = ppyolov2(num_classes=80)
    model.eval()
    import jax
    from paddle_tpu.framework.functional import (extract_params,
                                                 extract_buffers,
                                                 functional_call)
    params = extract_params(model)
    buffers = extract_buffers(model)

    def fwd(p, b, img):
        out, _ = functional_call(model, p, b, (paddle.Tensor(img),),
                                 training=False)
        return out
    jfwd = jax.jit(fwd)
    rows = []
    for batch in ((1, 8) if on_tpu else (1,)):
        img = np.random.RandomState(0).rand(
            batch, 3, size, size).astype(np.float32)
        out = jfwd(params, buffers, img)    # compile
        _ = np.asarray(jax.tree_util.tree_leaves(out)[0])
        n = 10 if on_tpu else 2
        reps = 3 if on_tpu else 1
        per_rep = []
        for _ in range(reps):
            t0 = time.time()
            for _ in range(n):
                out = jfwd(params, buffers, img)
            _ = np.asarray(jax.tree_util.tree_leaves(out)[0])
            per_rep.append((time.time() - t0) / n)
        med = statistics.median(per_rep)
        rows.append({'metric': 'yolo_infer_latency_ms',
                     'value': round(med * 1e3 / batch, 2), 'unit': 'ms/img',
                     'batch': batch,
                     'batch_latency_ms': round(med * 1e3, 2),
                     'qps': round(batch / med, 2),
                     'spread_ms': round((max(per_rep) - min(per_rep)) * 1e3,
                                        2),
                     'reps': reps, 'image_size': size,
                     'degraded': not on_tpu})
    return rows


def bench_gpt_decode(on_tpu):
    """Autoregressive decode throughput (tokens/sec) through the
    single-dispatch scan decode (GPTForCausalLM.generate: jitted prefill
    + ONE lax.scan program — reference serving path analog:
    AnalysisPredictor, analysis_predictor.cc:381).

    Reports the HBM roofline alongside: cached decode is weight-bound —
    each token step must stream the bf16 weights once, so
    steps/s <= HBM_BW / param_bytes, tokens/s <= batch * that.
    """
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        batch, prompt_len, new_tokens = 8, 128, 128
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        dropout=0.0)
        batch, prompt_len, new_tokens = 2, 8, 16
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    rows = []

    from paddle_tpu.slim import streamed_bytes as stream_bytes
    param_bytes = stream_bytes(model)
    hbm = 819e9 if on_tpu else 50e9                 # v5e HBM BW
    # decode is weight-streaming-bound, so tokens/s should scale near-
    # linearly with batch until compute catches up: measure two points
    batches = (batch, batch * 4) if on_tpu else (batch,)
    import os
    profile_dir = os.environ.get('PADDLE_TPU_BENCH_PROFILE_DECODE')

    def measure(metric, weight_bytes, extra_fields, profiled_batch=None):
        """One metric's batch sweep; shared protocol for every variant
        (a drifting copy of the timing loop is how the profiled-run-
        equals-timed-run bug slipped in)."""
        for b in batches:
            try:
                prompt = paddle.to_tensor(
                    rng.randint(0, cfg.vocab_size, (b, prompt_len)).astype(
                        np.int32))
                out = model.generate(prompt,
                                     max_new_tokens=new_tokens)  # compile
                _ = out.numpy()
                if profiled_batch == b:
                    # on-chip trace of the already-compiled decode
                    # program: the data that names the next decode
                    # byte-mover. The traced run is SEPARATE from the
                    # timed one below — profiler overhead must not leak
                    # into the reported tokens/sec
                    import jax
                    jax.profiler.start_trace(profile_dir)
                    try:
                        _ = model.generate(
                            prompt, max_new_tokens=new_tokens).numpy()
                    finally:
                        # an unmatched start_trace would leave the
                        # profiler running for every later point
                        jax.profiler.stop_trace()
                t0 = time.time()
                out = model.generate(prompt, max_new_tokens=new_tokens)
                _ = out.numpy()
                dt = time.time() - t0
            except Exception as e:
                # a failed larger-batch point must not discard the
                # smaller one already measured
                rows.append({'metric': metric, 'batch': b,
                             'error': repr(e)[:300]})
                continue
            toks = b * new_tokens / dt
            roofline = b * hbm / weight_bytes
            row = {'metric': metric, 'value': round(toks, 2),
                   'unit': 'tokens/sec', 'batch': b,
                   'tokens_per_sec_per_seq': round(toks / b, 2),
                   'roofline_tokens_per_sec': round(roofline, 0),
                   'roofline_frac': round(toks / roofline, 4),
                   'prompt_len': prompt_len, 'new_tokens': new_tokens,
                   'degraded': not on_tpu}
            row.update(extra_fields)
            rows.append(row)

    measure('gpt_decode_tokens_per_sec', param_bytes, {},
            profiled_batch=batch if profile_dir else None)

    # weight-only int8 serving variant (slim.weight_only): halves the
    # streamed bytes on the transformer Linears — a DIFFERENT model
    # (quantized weights), reported under its own metric with its own
    # roofline. Reference analog: AnalysisPredictor int8 deployments.
    try:
        from paddle_tpu.slim import quantize_weight_only
        quantize_weight_only(model)
        q_bytes = stream_bytes(model)
    except Exception as e:
        rows.append({'metric': 'gpt_decode_int8w_tokens_per_sec',
                     'error': repr(e)[:300]})
        return rows
    measure('gpt_decode_int8w_tokens_per_sec', q_bytes,
            {'stream_bytes_int8': q_bytes, 'stream_bytes_bf16': param_bytes})
    return rows


def _serving_workload(n_req, lens, mnt, mean_gap, vocab, tenants=None):
    """The serving rungs' shared workload spec: seeded Poisson arrivals
    with a prompt-length ladder, expressed in the capacity.workload
    language. Parameters and RNG streams match the retired hand-rolled
    generators exactly (capacity.workload pins the parity), so stored
    bench bests stay comparable; rows carry the spec hash."""
    from paddle_tpu.capacity import workload
    return workload.WorkloadSpec(
        requests=n_req, seed=0, vocab_size=vocab,
        arrival={'process': 'poisson', 'mean_gap_s': mean_gap},
        lengths={'dist': 'ladder', 'lens': list(lens)},
        output={'dist': 'fixed', 'len': mnt}, tenants=tenants)


def _perf_fields(eng, t_cold=None, bursts=None, wall=None):
    """Perf-introspection fields for a serving bench row: cold/warm
    compile seconds, post-warmup recompile count, and the cost-model
    MFU/roofline block over the engine's steady-state program (decode,
    or the verify forward under speculation)."""
    out = {}
    if t_cold is not None:
        out['compile_s_cold'] = round(t_cold, 3)
    out['recompiles'] = eng.perf.recompiles
    try:
        est = eng.perf_estimate(bursts=bursts, wall_seconds=wall)
    except Exception:
        est = None
    if est:
        out['compile_s_warm'] = round(est['compile_s_warm'], 3)
        intensity = est.get('arithmetic_intensity')
        if intensity is not None and intensity != float('inf'):
            out['arithmetic_intensity'] = round(intensity, 2)
        out['roofline_bound'] = est['roofline_bound']
        if 'mfu_est' in est:
            out['mfu_est'] = round(est['mfu_est'], 4)
    try:
        from paddle_tpu.framework import compile_cache
        hr = compile_cache.hit_rate()
        if hr is not None:
            out['compile_cache_hit_rate'] = round(hr, 4)
    except Exception:
        pass
    return out


def _drive_cb(engine, prompts, arrivals, mnt):
    """Feed the engine its arrival trace in real time and drain it."""
    from paddle_tpu.serving.metrics import ServingMetrics
    engine.metrics = ServingMetrics()     # drop warmup samples
    reqs = []
    i = 0
    t0 = time.time()
    while i < len(prompts) or engine.scheduler.pending:
        now = time.time() - t0
        while i < len(prompts) and arrivals[i] <= now:
            reqs.append(engine.add_request(prompts[i], max_new_tokens=mnt))
            i += 1
        if engine.scheduler.pending:
            engine.step()
        elif i < len(prompts):
            time.sleep(min(arrivals[i] - now, 0.01))
    dt = time.time() - t0
    toks = sum(len(r.tokens) for r in reqs)
    return toks / dt, engine.metrics.report()


def _drive_sequential(model, prompts, arrivals, mnt):
    """Baseline: one generate() per request, strictly in arrival order
    (the pre-continuous-batching serving shape: each request owns the
    model until it finishes)."""
    import paddle_tpu as paddle
    lat = []
    t0 = time.time()
    for p, arr in zip(prompts, arrivals):
        now = time.time() - t0
        if now < arr:
            time.sleep(arr - now)
        s0 = time.time()
        _ = model.generate(paddle.to_tensor([p]),
                           max_new_tokens=mnt).numpy()
        lat.append(time.time() - s0)
    dt = time.time() - t0
    return len(prompts) * mnt / dt, statistics.median(lat)


def bench_serving(on_tpu):
    """Continuous-batching serving rung: tok/s, p50/p99 per-token
    latency and slot occupancy vs the sequential generate() baseline
    under a Poisson arrival trace, plus the tok/s-vs-slot-count
    saturation curve (8/16/32) and an int8 weight-only variant.

    The headline comparison is throughput under load: sequential serving
    runs [1, hidden] decode GEMMs while requests queue; the engine keeps
    the same GEMMs at slot-count batch. Same prompts, same trace, same
    greedy sampling — and the engine's greedy tokens are asserted
    identical to generate()'s in tests/test_serving.py, so the speedup
    is not bought with drift.
    """
    import paddle_tpu as paddle
    from paddle_tpu.serving import ContinuousBatchingEngine
    from paddle_tpu.slim import quantize_weight_only, streamed_bytes
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        lens, mnt, n_req = (32, 64, 96, 128), 64, 32
        max_len, chunk, block = 256, 32, 8
        slot_curve, mean_gap = (8, 16, 32), 0.02
    else:
        # big enough that decode GEMMs outweigh host dispatch (a
        # hidden-64 toy is dispatch-bound and hides the batching win),
        # arrival rate high enough that serving is service-bound — the
        # regime continuous batching exists for
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        lens, mnt, n_req = (8, 16, 24, 32), 32, 24
        max_len, chunk, block = 64, 32, 8
        slot_curve, mean_gap = (8, 16, 32), 0.002
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    spec = _serving_workload(n_req, lens, mnt, mean_gap, cfg.vocab_size)
    trace = spec.generate()
    prompts = trace.prompts()
    arrivals = trace.arrivals()
    rows = []

    def run_variant(tag, extra):
        # sequential baseline: compile every (prompt_len, mnt) signature
        # before timing — serving steady state, not cold-start
        for n0 in lens:
            _ = model.generate(paddle.to_tensor([[0] * n0]),
                               max_new_tokens=mnt).numpy()
        seq_tps, seq_lat = _drive_sequential(model, prompts, arrivals, mnt)
        for num_slots in slot_curve:
            eng = ContinuousBatchingEngine(
                model, num_slots=num_slots, max_len=max_len,
                prefill_chunk=chunk, decode_block=block)
            t0c = time.time()
            eng.generate(prompts[:2], max_new_tokens=2)     # compile
            t_cold = time.time() - t0c
            b0 = eng.timeline.steps
            w0 = time.time()
            if num_slots == slot_curve[0]:
                # headline point: the real-time Poisson trace
                tps, rep = _drive_cb(eng, prompts, arrivals, mnt)
                row = {'metric': 'serving_cb_tokens_per_sec' + tag,
                       'value': round(tps, 2), 'unit': 'tokens/sec',
                       'num_slots': num_slots,
                       'latency_p50_ms': round(rep['latency_p50_ms'], 3),
                       'latency_p99_ms': round(rep['latency_p99_ms'], 3),
                       'occupancy_mean': round(rep['occupancy_mean'], 3),
                       'sequential_tokens_per_sec': round(seq_tps, 2),
                       'sequential_latency_median_s': round(seq_lat, 4),
                       'speedup_vs_sequential': round(tps / seq_tps, 2),
                       'trace': 'poisson', 'mean_gap_s': mean_gap,
                       'requests': n_req, 'new_tokens': mnt,
                       'workload_spec': spec.hash,
                       'traces': eng.compiled_sizes(),
                       'degraded': not on_tpu}
            else:
                # saturation curve: everything queued at t=0
                tps, rep = _drive_cb(eng, prompts, [0.0] * n_req, mnt)
                row = {'metric': 'serving_cb_tokens_per_sec' + tag,
                       'value': round(tps, 2), 'unit': 'tokens/sec',
                       'num_slots': num_slots,
                       'occupancy_mean': round(rep['occupancy_mean'], 3),
                       'trace': 'burst', 'requests': n_req,
                       'new_tokens': mnt, 'workload_spec': spec.hash,
                       'degraded': not on_tpu}
            row.update(_perf_fields(eng, t_cold,
                                    eng.timeline.steps - b0,
                                    time.time() - w0))
            row.update(extra)
            rows.append(row)

    run_variant('', {'stream_bytes': streamed_bytes(model)})
    try:
        quantize_weight_only(model)
        # quantization invalidates generate()'s compiled caches (the
        # buffer pytree changed shape); they re-key automatically
        run_variant('_int8w', {'stream_bytes': streamed_bytes(model)})
    except Exception as e:
        rows.append({'metric': 'serving_cb_tokens_per_sec_int8w',
                     'error': repr(e)[:300]})
    return rows


def _drive_paged(engine, prompts, arrivals, mnt):
    """_drive_cb plus the paged engine's capacity counters: returns
    (tok/s, report, peak pages in use across steps)."""
    from paddle_tpu.serving.metrics import ServingMetrics
    engine.metrics = ServingMetrics()     # drop warmup samples
    reqs, peak = [], 0
    i = 0
    t0 = time.time()
    while i < len(prompts) or engine.scheduler.pending:
        now = time.time() - t0
        while i < len(prompts) and arrivals[i] <= now:
            reqs.append(engine.add_request(prompts[i], max_new_tokens=mnt))
            i += 1
        if engine.scheduler.pending:
            engine.step()
            peak = max(peak, engine.pages.in_use)
        elif i < len(prompts):
            time.sleep(min(arrivals[i] - now, 0.01))
    dt = time.time() - t0
    toks = sum(len(r.tokens) for r in reqs)
    return toks / dt, engine.metrics.report(), peak


def bench_serving_paged(on_tpu):
    """Paged-KV serving rung: page-granular KV + prefix sharing + spec
    decode vs the PR-3 slot engine at the SAME occupancy, on a shared-
    system-prompt workload (every request opens with the same system
    prefix, the traffic shape prefix caching exists for).

    Rows (all keyed by workload/page_size/spec_k for the regression
    gate): the headline paged tok/s row carries the slot engine's tok/s
    on the identical trace plus prefix hit-rate, prefilled-token count
    and peak pages-in-use as fields; prefix hit-rate and spec accept-
    rate also get their own gated rows (both regress DOWN). Greedy
    parity across all three modes is asserted in tests/test_serving.py,
    so none of these numbers is bought with output drift.
    """
    import paddle_tpu as paddle
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedContinuousBatchingEngine)
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        sys_len, tail_lens, mnt, n_req = 64, (8, 16, 24, 32), 64, 32
        max_len, chunk, block, num_seqs, page = 256, 32, 8, 8, 16
    else:
        # same regime as bench_serving's CPU branch: decode GEMMs big
        # enough to outweigh host dispatch, burst arrivals so the run is
        # service-bound at full occupancy
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        sys_len, tail_lens, mnt, n_req = 32, (4, 8, 12, 16), 32, 24
        max_len, chunk, block, num_seqs, page = 96, 32, 8, 8, 16
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    from paddle_tpu.capacity import workload
    spec = workload.WorkloadSpec(
        requests=n_req, seed=0, vocab_size=cfg.vocab_size,
        arrival={'process': 'burst'},        # everything at t=0
        lengths={'dist': 'ladder', 'lens': list(tail_lens)},
        output={'dist': 'fixed', 'len': mnt},
        prefix={'len': sys_len, 'groups': 1, 'prob': 1.0})
    trace = spec.generate()
    prompts = trace.prompts()
    arrivals = trace.arrivals()              # burst: full occupancy
    base = {'new_tokens': mnt, 'num_slots': num_seqs, 'page_size': page,
            'workload': 'shared_prefix', 'trace': 'burst',
            'workload_spec': spec.hash,
            'requests': n_req, 'degraded': not on_tpu}
    rows = []

    # slot engine on the identical trace = the same-occupancy baseline
    slot = ContinuousBatchingEngine(model, num_slots=num_seqs,
                                    max_len=max_len, prefill_chunk=chunk,
                                    decode_block=block)
    slot.generate(prompts[:2], max_new_tokens=2)             # compile
    slot_tps, _ = _drive_cb(slot, prompts, arrivals, mnt)

    for spec_k in (0, 4):
        eng = PagedContinuousBatchingEngine(
            model, num_seqs=num_seqs, max_len=max_len, page_size=page,
            prefill_chunk=chunk, decode_block=block, spec_k=spec_k)
        t0c = time.time()
        eng.generate(prompts[:2], max_new_tokens=2)          # compile
        t_cold = time.time() - t0c
        b0 = eng.timeline.steps
        w0 = time.time()
        tps, rep, peak = _drive_paged(eng, prompts, arrivals, mnt)
        wall = time.time() - w0
        tag = '_spec' if spec_k else ''
        rows.append(dict(base, metric='serving_paged_tokens_per_sec' + tag,
                         value=round(tps, 2), unit='tokens/sec',
                         spec_k=spec_k,
                         slot_tokens_per_sec=round(slot_tps, 2),
                         speedup_vs_slot=round(tps / slot_tps, 3),
                         prefix_hit_rate=round(rep['prefix_hit_rate'], 3),
                         prefill_tokens=rep['prefill_tokens'],
                         pages_in_use_peak=peak,
                         spec_accept_rate=round(rep['spec_accept_rate'], 3),
                         occupancy_mean=round(rep['occupancy_mean'], 3),
                         traces=eng.compiled_sizes(),
                         **_perf_fields(eng, t_cold,
                                        eng.timeline.steps - b0, wall)))
        if not spec_k:
            rows.append(dict(base, metric='serving_paged_prefix_hit_rate',
                             value=round(rep['prefix_hit_rate'], 4),
                             unit='ratio', spec_k=spec_k,
                             prefill_tokens=rep['prefill_tokens']))
        else:
            rows.append(dict(base, metric='serving_paged_spec_accept_rate',
                             value=round(rep['spec_accept_rate'], 4),
                             unit='ratio', spec_k=spec_k,
                             spec_proposed=rep['spec_proposed'],
                             spec_accepted=rep['spec_accepted']))
    return rows


def bench_serving_gateway(on_tpu):
    """Multi-replica gateway rung: the Poisson-arrival chaos workload
    from ISSUE 8 — a 2-replica ServingGateway under the bench_serving
    arrival trace, measured clean and with one replica killed mid-burst.

    Rows (keyed by replicas/kill_at/policy for the regression gate): the
    clean gateway tok/s, the chaos-run tok/s (kill at 50% of
    submissions, failover count as a field), and the chaos completed
    ratio — the acceptance number, which must stay 1.0: every request
    finishes even though half the pool died mid-run. Exact-token parity
    of failed-over requests is asserted in
    tests/test_serving_gateway.py, so the throughput is not bought with
    drift or drops.
    """
    import paddle_tpu as paddle
    from paddle_tpu.monitor.registry import MetricRegistry
    from paddle_tpu.serving import ContinuousBatchingEngine, ServingGateway
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        lens, mnt, n_req = (32, 64, 96, 128), 64, 32
        max_len, chunk, block, num_slots = 256, 32, 8, 8
        mean_gap = 0.02
    else:
        # same service-bound regime as bench_serving's CPU branch
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        lens, mnt, n_req = (8, 16, 24, 32), 32, 24
        max_len, chunk, block, num_slots = 64, 32, 8, 8
        mean_gap = 0.002
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    from paddle_tpu.capacity.replay import replay as replay_trace
    spec = _serving_workload(n_req, lens, mnt, mean_gap, cfg.vocab_size)
    trace = spec.generate()
    prompts = trace.prompts()
    replicas, kill_frac = 2, 0.5

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=num_slots, max_len=max_len,
            prefill_chunk=chunk, decode_block=block)

    def drive(kill_at):
        reg = MetricRegistry()
        gw = ServingGateway(factory, replicas=replicas, registry=reg)
        t0c = time.time()
        gw.generate(prompts[:replicas], max_new_tokens=2)     # compile
        t_cold = time.time() - t0c
        b0 = sum(r.engine.timeline.steps for r in gw.pool)
        gw.start()
        kill_i = None if kill_at is None else int(n_req * kill_at)

        def maybe_kill(i):
            if kill_i is not None and i == kill_i:
                gw.kill_replica(1)

        res = replay_trace(gw, trace, max_new_tokens=mnt,
                                     timeout=600,
                                     before_submit=maybe_kill)
        bursts = sum(r.engine.timeline.steps for r in gw.pool) - b0
        gw.shutdown()
        failovers = int(reg.get('gateway_failover_total').value())
        # replica 0 always survives the chaos run: its decode program is
        # representative, and bursts summed pool-wide make the MFU an
        # aggregate utilization over the whole gateway
        perf = _perf_fields(gw.pool[0].engine, t_cold, bursts, res.wall_s)
        return (res.tokens_per_sec, res.completed_ratio, failovers,
                gw.report(), perf)

    base = {'unit': 'tokens/sec', 'trace': 'poisson',
            'mean_gap_s': mean_gap, 'requests': n_req, 'new_tokens': mnt,
            'num_slots': num_slots, 'replicas': replicas,
            'policy': 'least_loaded', 'workload_spec': spec.hash,
            'degraded': not on_tpu}
    rows = []
    tps, ratio, fo, rep, perf = drive(None)
    rows.append(dict(base, metric='serving_gateway_tokens_per_sec',
                     value=round(tps, 2), kill_at='none', failovers=fo,
                     completed_ratio=round(ratio, 4), **perf))
    tps, ratio, fo, rep, perf = drive(kill_frac)
    rows.append(dict(base, metric='serving_gateway_tokens_per_sec_chaos',
                     value=round(tps, 2), kill_at=kill_frac, failovers=fo,
                     completed_ratio=round(ratio, 4),
                     replicas_alive=rep['replicas_alive'], **perf))
    rows.append(dict(base, metric='serving_gateway_completed_ratio',
                     value=round(ratio, 4), unit='ratio',
                     kill_at=kill_frac, failovers=fo))
    return rows


def bench_serving_gateway_tenants(on_tpu):
    """Mixed-tenant gateway rung (ISSUE 15): the Poisson workload split
    across two tenants ('premium' short prompts, 'batch' long prompts)
    through a clean 2-replica gateway, observed through the wide-event
    request log rather than the aggregate counters.

    Rows: one per-tenant TTFT p50 row per tenant (unit 'ms', keyed by
    the `tenant` aux field — the regression gate checks these
    lower-is-better), plus a kv attribution row whose value is the
    per-tenant KV page·second split. Every row carries the cross-check
    fields `kv_events_page_seconds` (sum over wide events) and
    `kv_pool_page_seconds` (sum of the slot allocators' pool-occupancy
    integrals): for the slot engine the two are equal by construction,
    and tools/request_report.py --kv-integral gates exactly that."""
    import paddle_tpu as paddle
    from paddle_tpu.monitor.events import (RequestLog,
                                           set_default_request_log)
    from paddle_tpu.monitor.registry import MetricRegistry
    from paddle_tpu.serving import ContinuousBatchingEngine, ServingGateway
    from paddle_tpu.serving.metrics import percentile
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        lens, mnt, n_req = (32, 64, 96, 128), 64, 32
        max_len, chunk, block, num_slots = 256, 32, 8, 8
        mean_gap = 0.02
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        lens, mnt, n_req = (8, 16, 24, 32), 32, 24
        max_len, chunk, block, num_slots = 64, 32, 8, 8
        mean_gap = 0.002
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    from paddle_tpu.capacity.replay import replay as replay_trace
    # premium gets the short half of the length ladder, batch the long
    # half — distinguishable TTFT profiles from one workload
    spec = _serving_workload(
        n_req, lens, mnt, mean_gap, cfg.vocab_size,
        tenants={'mode': 'round_robin', 'tenants': [
            {'name': 'premium',
             'lengths': {'dist': 'ladder',
                         'lens': list(lens[:len(lens) // 2])}},
            {'name': 'batch',
             'lengths': {'dist': 'ladder',
                         'lens': list(lens[len(lens) // 2:])}}]})
    trace = spec.generate()
    prompts = trace.prompts()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=num_slots, max_len=max_len,
            prefill_chunk=chunk, decode_block=block)

    # the log must be installed BEFORE construction: engines and the
    # gateway cache default_request_log() like they cache the tracer
    log = RequestLog(capacity=4 * n_req)
    prev_log = set_default_request_log(log)
    try:
        reg = MetricRegistry()
        gw = ServingGateway(factory, replicas=2, registry=reg)
        gw.generate(prompts[:2], max_new_tokens=2,
                    tenant='warmup')                          # compile
        gw.start()
        res = replay_trace(gw, trace, max_new_tokens=mnt,
                                     timeout=600)
        dt = res.wall_s
        gw.shutdown()
        # pool-occupancy integral across the pool; wide-event sum must
        # match it exactly for slot engines (warmup events included —
        # the integral saw those slots too)
        pool_ps = sum(rep.engine.allocator.page_seconds()
                      for rep in gw.pool)
        events = log.events()
    finally:
        set_default_request_log(prev_log)
    toks = res.tokens
    ev_ps = sum(e['kv_page_seconds'] for e in events)
    kv_by_tenant = {}
    ttft_by_tenant = {}
    for e in events:
        kv_by_tenant[e['tenant']] = (kv_by_tenant.get(e['tenant'], 0.0)
                                     + e['kv_page_seconds'])
        if e['first_token_t'] is not None and e['arrival_t'] is not None:
            ttft_by_tenant.setdefault(e['tenant'], []).append(
                (e['first_token_t'] - e['arrival_t']) * 1e3)
    base = {'trace': 'poisson', 'mean_gap_s': mean_gap,
            'requests': n_req, 'new_tokens': mnt,
            'num_slots': num_slots, 'replicas': 2, 'workload': 'mixed',
            'policy': 'least_loaded', 'workload_spec': spec.hash,
            'degraded': not on_tpu,
            'kv_events_page_seconds': round(ev_ps, 6),
            'kv_pool_page_seconds': round(pool_ps, 6)}
    rows = [dict(base, metric='serving_gateway_mixed_tokens_per_sec',
                 value=round(toks / dt, 2), unit='tokens/sec')]
    for tenant in ('premium', 'batch'):
        rows.append(dict(
            base, metric='serving_gateway_tenant_ttft_p50',
            value=round(percentile(ttft_by_tenant.get(tenant, [0.0]),
                                   50), 3),
            unit='ms', tenant=tenant,
            tenant_requests=sum(1 for e in events
                                if e['tenant'] == tenant),
            tenant_kv_page_seconds=round(
                kv_by_tenant.get(tenant, 0.0), 6)))
    return rows


def bench_serving_gateway_qos(on_tpu):
    """Overload-QoS rung (ISSUE 17): a mixed-tenant burst through a
    2-replica gateway behind the admission layer — 'premium' (priority
    1, unthrottled) vs 'bg' (token-bucket rate-limited, priority 0) —
    where the BACKGROUND arrival rate DOUBLES halfway through the run
    (a second bg-only trace overlaid from the midpoint). Graceful
    degradation is the claim: the gateway sheds background traffic
    (outcome='rejected' wide events) while every premium request
    completes (asserted == 1.0 inline) and the premium TTFT tail stays
    bounded.

    Rows for the regression gate: premium TTFT p99 (ms,
    lower-is-better), shed rate (ratio, lower-is-better — a regression
    here means the policy started over-shedding the same workload), and
    the premium completed ratio (ratio, higher-is-better)."""
    import paddle_tpu as paddle
    from paddle_tpu.capacity.replay import replay as replay_trace
    from paddle_tpu.capacity.workload import Trace
    from paddle_tpu.monitor.events import (RequestLog,
                                           set_default_request_log)
    from paddle_tpu.monitor.registry import MetricRegistry
    from paddle_tpu.serving import (ContinuousBatchingEngine, QosPolicy,
                                    ServingGateway, TenantClass)
    from paddle_tpu.serving.metrics import percentile
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        lens, mnt, n_req = (32, 64, 96, 128), 64, 32
        max_len, chunk, block, num_slots = 256, 32, 8, 8
        mean_gap, bg_rate, slo_ms = 0.02, 30.0, 2000.0
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        lens, mnt, n_req = (8, 16, 24, 32), 32, 24
        max_len, chunk, block, num_slots = 64, 32, 8, 8
        mean_gap, bg_rate, slo_ms = 0.002, 300.0, 5000.0
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    # steady half: premium + bg round-robin; burst half: a bg-only
    # trace at the SAME per-request gap overlaid from the midpoint, so
    # the background arrival rate doubles while premium's is unchanged
    spec = _serving_workload(
        n_req, lens, mnt, mean_gap, cfg.vocab_size,
        tenants={'mode': 'round_robin', 'tenants': [
            {'name': 'premium'}, {'name': 'bg'}]})
    burst_spec = _serving_workload(
        n_req // 2, lens, mnt, mean_gap, cfg.vocab_size,
        tenants={'mode': 'round_robin', 'tenants': [{'name': 'bg'}]})
    a, b = spec.generate(), burst_spec.generate()
    t_mid = float(a.arrival[-1]) * 0.5
    bg_id = a.tenant_names.index('bg')
    arr = np.concatenate([a.arrival, b.arrival + t_mid])
    order = np.argsort(arr, kind='stable')
    trace = Trace(
        arr[order],
        np.concatenate([a.prompt_len, b.prompt_len])[order],
        np.concatenate([a.new_tokens, b.new_tokens])[order],
        np.concatenate([a.tenant_id,
                        np.full(len(b), bg_id, np.int64)])[order],
        a.tenant_names,
        np.full(len(order), -1, np.int64),
        np.zeros(len(order), np.int64),
        meta={'vocab_size': cfg.vocab_size, 'spec': {'seed': 0}})
    prompts = trace.prompts()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=num_slots, max_len=max_len,
            prefill_chunk=chunk, decode_block=block)

    def policy():
        return QosPolicy(classes=[
            TenantClass('premium', priority=1),
            TenantClass('bg', rate=bg_rate, burst=max(4, num_slots),
                        priority=0)])

    log = RequestLog(capacity=4 * len(trace))
    prev_log = set_default_request_log(log)
    try:
        reg = MetricRegistry()
        gw = ServingGateway(factory, replicas=2, admission=policy(),
                            registry=reg)
        gw.generate(prompts[:2], max_new_tokens=2,
                    tenant='warmup')                          # compile
        gw.start()
        res = replay_trace(gw, trace, max_new_tokens=mnt, timeout=600)
        gw.shutdown()
        events = [e for e in log.events() if e['tenant'] != 'warmup']
    finally:
        set_default_request_log(prev_log)
    tenants = trace.tenants()
    premium = [h for h, t in zip(res.handles, tenants) if t == 'premium']
    shed = sum(1 for h in res.handles if h.error is not None)
    shed_rate = shed / float(len(res.handles))
    prem_done = sum(1 for h in premium if h.done and h.error is None)
    prem_ratio = prem_done / float(len(premium))
    if prem_ratio != 1.0:
        raise AssertionError(
            'premium completed_ratio %.4f != 1.0 under background burst'
            % prem_ratio)
    prem_ttft = [(e['first_token_t'] - e['arrival_t']) * 1e3
                 for e in events
                 if e['tenant'] == 'premium'
                 and e['first_token_t'] is not None]
    p99 = percentile(prem_ttft, 99) or 0.0
    rejected_events = sum(1 for e in events if e['outcome'] == 'rejected')
    if rejected_events != shed:
        raise AssertionError(
            'rejected wide events (%d) != shed handles (%d)'
            % (rejected_events, shed))
    base = {'trace': 'poisson+bg_burst', 'mean_gap_s': mean_gap,
            'requests': len(trace), 'new_tokens': mnt,
            'num_slots': num_slots, 'replicas': 2,
            'policy': 'least_loaded', 'bg_rate': bg_rate,
            'bg_doubles_at_s': round(t_mid, 4),
            'workload_spec': spec.hash, 'burst_spec': burst_spec.hash,
            'degraded': not on_tpu}
    return [
        dict(base, metric='serving_gateway_qos_premium_ttft_p99',
             value=round(p99, 3), unit='ms', slo_ttft_ms=slo_ms,
             slo_ok=bool(p99 <= slo_ms),
             premium_requests=len(premium)),
        dict(base, metric='serving_gateway_qos_shed_rate',
             value=round(shed_rate, 4), unit='ratio', shed=shed),
        dict(base, metric='serving_gateway_qos_premium_completed_ratio',
             value=round(prem_ratio, 4), unit='ratio',
             premium_requests=len(premium)),
    ]


def bench_serving_gateway_multimodel(on_tpu):
    """Multi-model serving rung (ISSUE 19): N models behind one
    2-replica gateway of ModelHost replicas, a zipf-mixed Poisson burst
    routed by model affinity, and a zero-downtime `rollout()` of the
    head model's weights fired MID-burst from the replay hook.

    Acceptance, asserted inline (a broken swap must fail the rung, not
    ship a row):
      * completed_ratio == 1.0 — every request before, during and
        after the weight swap finishes (drain-never-kill applied to
        weights instead of replicas);
      * per-model wide-event attribution matches the workload's model
        mix EXACTLY (the trace is the oracle for who asked for what);
      * the warm bring-up of the new version reports zero persistent
        compile-cache misses — same program shapes, new weights;
      * weight paging proof on a budgeted host: resident bytes never
        exceed the byte budget and the eviction counters match the LRU
        oracle replayed in plain python.
    """
    import paddle_tpu as paddle
    from paddle_tpu.capacity.replay import replay as replay_trace
    from paddle_tpu.framework import io_save
    from paddle_tpu.monitor.events import (RequestLog,
                                           set_default_request_log)
    from paddle_tpu.monitor.registry import MetricRegistry
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    ModelAffinityRouter, ModelHost,
                                    ModelRegistry, ServingGateway)
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
    import shutil
    import tempfile

    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        lens, mnt, n_req = (32, 64, 96, 128), 64, 32
        max_len, chunk, block, num_slots = 256, 32, 8, 8
        mean_gap = 0.02
    else:
        # smaller than the other gateway rungs: the rung builds
        # n_models+1 engine instances, so weights are kept light
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        lens, mnt, n_req = (8, 16, 24, 32), 16, 24
        max_len, chunk, block, num_slots = 64, 32, 8, 8
        mean_gap = 0.002
    n_models, swap_frac = 3, 0.5
    swap_at = int(n_req * swap_frac)
    head = 'model_000'

    root = tempfile.mkdtemp(prefix='bench_registry_')
    try:
        # publish one distinctly-seeded artifact per model, plus the
        # head model's v2 (the weights the mid-burst rollout ships)
        reg = ModelRegistry(root=root)
        for i in range(n_models):
            paddle.seed(100 + i)
            m = GPTForCausalLM(cfg)
            reg.publish('model_%03d' % i, 'v1', m.state_dict())
        paddle.seed(200)
        reg.publish(head, 'v2', GPTForCausalLM(cfg).state_dict())
        nbytes = reg.entry(head, 'v1').nbytes

        def engine_for(entry):
            m = GPTForCausalLM(cfg)
            m.set_state_dict(io_save.load(entry.path))
            if on_tpu:
                m.bfloat16()
            m.eval()
            return ContinuousBatchingEngine(
                m, num_slots=num_slots, max_len=max_len,
                prefill_chunk=chunk, decode_block=block)

        spec = _serving_workload(
            n_req, lens, mnt, mean_gap, cfg.vocab_size)
        spec.models = {'mode': 'zipf', 'count': n_models}
        trace = spec.generate()

        def host_factory():
            # serving hosts get headroom: every model plus the rollout's
            # incoming version must be co-resident under load
            return ModelHost(reg, engine_for,
                             byte_budget=(n_models + 2) * nbytes,
                             max_len=max_len)

        log = RequestLog(capacity=4 * n_req)
        prev_log = set_default_request_log(log)
        try:
            mreg = MetricRegistry()
            gw = ServingGateway(host_factory, replicas=2, registry=mreg,
                                router=ModelAffinityRouter())
            t0c = time.time()
            gw.generate(trace.prompts()[:2], max_new_tokens=2,
                        model=head, tenant='warmup')          # compile
            t_cold = time.time() - t0c
            gw.start()
            rollout = {}

            def swap(i):
                if i == swap_at:
                    rollout.update(gw.rollout(head, 'v2'))

            res = replay_trace(gw, trace, max_new_tokens=mnt,
                               timeout=600, before_submit=swap)
            gw.shutdown()
            events = [e for e in log.events() if e['tenant'] != 'warmup']
        finally:
            set_default_request_log(prev_log)

        if res.completed_ratio != 1.0:
            raise AssertionError(
                'rollout lost requests: completed_ratio %.4f != 1.0'
                % res.completed_ratio)
        if not rollout or rollout.get('to_version') != 'v2':
            raise AssertionError('mid-burst rollout did not run: %r'
                                 % (rollout,))
        if int(rollout.get('cache_misses') or 0) > 0:
            raise AssertionError(
                'warm bring-up missed the compile cache: %r' % (rollout,))
        # the trace is the attribution oracle: wide events per model
        # must equal the workload's model mix exactly
        ev_mix = {}
        for e in events:
            ev_mix[e['model']] = ev_mix.get(e['model'], 0) + 1
        if ev_mix != trace.model_mix():
            raise AssertionError(
                'wide-event attribution %r != trace model mix %r'
                % (ev_mix, trace.model_mix()))

        # ---- weight paging proof: budget holds 2 of the 3 models ----
        pager = ModelHost(reg, engine_for,
                          byte_budget=2 * nbytes + nbytes // 2)
        oracle_resident, oracle_evicted = [], []
        max_resident = 0
        for i in list(range(n_models)) * 2:
            key = ('model_%03d' % i, 'v1')
            pager.load(*key)
            if key in oracle_resident:
                oracle_resident.remove(key)
            while len(oracle_resident) >= 2:
                oracle_evicted.append(oracle_resident.pop(0))
            oracle_resident.append(key)
            if pager.resident_bytes > pager.byte_budget:
                raise AssertionError(
                    'resident bytes %d exceed budget %d'
                    % (pager.resident_bytes, pager.byte_budget))
            max_resident = max(max_resident, len(pager.resident_models()))
        evictions = {
            'model_%03d' % i: int(pager._m_evictions.labels(
                model='model_%03d' % i).value())
            for i in range(n_models)}
        want = {'model_%03d' % i:
                sum(1 for k in oracle_evicted if k[0] == 'model_%03d' % i)
                for i in range(n_models)}
        if evictions != want:
            raise AssertionError('eviction counters %r != LRU oracle %r'
                                 % (evictions, want))
        pager.shutdown()

        base = {'trace': 'poisson', 'mean_gap_s': mean_gap,
                'requests': n_req, 'new_tokens': mnt,
                'num_slots': num_slots, 'replicas': 2,
                'n_models': n_models, 'swap_at': swap_frac,
                'policy': 'model_affinity', 'workload_spec': spec.hash,
                'degraded': not on_tpu}
        toks = sum(int(e['output_tokens'] or 0) for e in events)
        rows = [
            dict(base, metric='serving_gateway_multimodel_tokens_per_sec',
                 value=round(res.tokens_per_sec, 2), unit='tokens/sec',
                 compile_s_cold=round(t_cold, 3),
                 model_mix=trace.model_mix(), event_tokens=toks),
            dict(base,
                 metric='serving_gateway_multimodel_completed_ratio',
                 value=round(res.completed_ratio, 4), unit='ratio'),
            dict(base, metric='serving_gateway_rollout_warm_load_s',
                 value=round(float(rollout.get('load_s') or 0.0), 3),
                 unit='s', model=head,
                 cache_hits=int(rollout.get('cache_hits') or 0),
                 cache_misses=int(rollout.get('cache_misses') or 0)),
            dict(base, metric='registry_paging_evictions',
                 value=sum(evictions.values()), unit='count',
                 byte_budget=pager.byte_budget,
                 artifact_bytes=nbytes, max_models_resident=max_resident,
                 resident_bytes_final=pager.resident_bytes),
        ]
        return rows
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_serving_fabric(on_tpu):
    """Serving-fabric rung (ISSUE 20): the gateway fronting REAL worker
    processes over the socket transport.

    Three measurements, each on a fresh 2-process worker pair:

    - clean Poisson burst tok/s (the cross-process tax vs the in-proc
      bench_serving_gateway rung is this row's whole point);
    - the same burst with one worker SIGKILLed mid-run — the chaos
      acceptance: completed_ratio must stay 1.0 (token parity of
      failed-over requests is pinned in tests/test_serving_fabric.py);
    - a shared-system-prompt workload routed by LeastLoaded vs the
      gateway's PrefixAffinityRouter over paged workers: the prefix
      directory's hit-rate win is the tracked value.

    Rows are keyed by transport/n_procs (+ policy for the router pair)
    in the regression gate's aux config.
    """
    from paddle_tpu.capacity import workload
    from paddle_tpu.capacity.replay import replay as replay_trace
    from paddle_tpu.monitor import events as _events
    from paddle_tpu.monitor.registry import MetricRegistry
    from paddle_tpu.serving import ServingGateway
    from paddle_tpu.serving.fabric import (PrefixAffinityRouter,
                                           SocketReplica, spawn_worker)

    n_procs = 2
    vocab = 211                      # the preset zoo's vocab
    # prompt + 16 new tokens must fit the gpt-nano preset's max_len=32
    spec = _serving_workload(16, (4, 8, 12, 14), 16, 0.002, vocab)
    trace = spec.generate()
    prompts = trace.prompts()

    def fabric_gateway(handles, router=None):
        gw = ServingGateway(None, replicas=0, router=router,
                            registry=MetricRegistry())
        for h in handles:
            gw.adopt_replica(SocketReplica(
                h.endpoint, metrics_url=h.metrics_url,
                poll_interval=0.002).connect())
        return gw

    def drive(preset, wl_trace, mnt, kill_at=None, router=None):
        handles = [spawn_worker(preset=preset) for _ in range(n_procs)]
        log = _events.RequestLog(capacity=4096)
        prev = _events.set_default_request_log(log)
        try:
            gw = fabric_gateway(handles, router=router)
            t0c = time.time()
            gw.generate(wl_trace.prompts()[:n_procs],
                        max_new_tokens=2)             # compile workers
            t_cold = time.time() - t0c
            log.clear()
            gw.start()
            kill_i = None if kill_at is None else \
                int(len(wl_trace) * kill_at)

            def maybe_kill(i):
                if kill_i is not None and i == kill_i:
                    handles[0].kill()                 # SIGKILL, no drain

            res = replay_trace(gw, wl_trace, max_new_tokens=mnt,
                               timeout=600, before_submit=maybe_kill)
            failovers = int(gw.registry.get(
                'gateway_failover_total').value())
            gw.shutdown()
            evs = log.events()
            hit = sum(e.get('prefix_hit_tokens') or 0 for e in evs)
            prompt_toks = sum(e.get('prompt_tokens') or 0 for e in evs)
            return (res, failovers, t_cold,
                    hit / prompt_toks if prompt_toks else 0.0)
        finally:
            _events.set_default_request_log(prev)
            for h in handles:
                h.cleanup()

    base = {'unit': 'tokens/sec', 'trace': 'poisson',
            'transport': 'socket', 'n_procs': n_procs, 'requests': 16,
            'new_tokens': 16, 'policy': 'least_loaded',
            'workload_spec': spec.hash, 'degraded': not on_tpu}
    rows = []
    res, fo, t_cold, _ = drive('gpt-nano', trace, 16)
    rows.append(dict(base, metric='serving_fabric_tokens_per_sec',
                     value=round(res.tokens_per_sec, 2), kill_at='none',
                     failovers=fo, compile_s_cold=round(t_cold, 3),
                     completed_ratio=round(res.completed_ratio, 4)))
    res, fo, t_cold, _ = drive('gpt-nano', trace, 16, kill_at=0.5)
    rows.append(dict(base, metric='serving_fabric_tokens_per_sec_chaos',
                     value=round(res.tokens_per_sec, 2), kill_at=0.5,
                     failovers=fo, compile_s_cold=round(t_cold, 3),
                     completed_ratio=round(res.completed_ratio, 4)))
    rows.append(dict(base, metric='serving_fabric_completed_ratio',
                     value=round(res.completed_ratio, 4), unit='ratio',
                     kill_at=0.5, failovers=fo))

    # shared-system-prompt workload over paged workers: 90% of requests
    # share a 24-token system prefix (3 pages at the preset's page
    # size 8) in 4 groups — more groups than replicas, so least-loaded
    # pays a cold miss per (group, replica) pair while affinity pays
    # one per group; short tails keep the prefix dominant. Max prompt
    # 24 + 8 = 32, + 8 new tokens fits gpt-nano-paged's max_len=64.
    pspec = workload.WorkloadSpec(
        requests=24, seed=1, vocab_size=vocab,
        arrival={'process': 'poisson', 'mean_gap_s': 0.002},
        lengths={'dist': 'ladder', 'lens': [4, 8]},
        output={'dist': 'fixed', 'len': 8},
        prefix={'len': 24, 'groups': 4, 'prob': 0.9})
    ptrace = pspec.generate()
    for router, policy in ((None, 'least_loaded'),
                           (PrefixAffinityRouter(page_size=8),
                            'prefix_affinity')):
        res, _, _, hit_rate = drive('gpt-nano-paged', ptrace, 8,
                                    router=router)
        rows.append(dict(base, metric='serving_fabric_prefix_hit_rate',
                         value=round(hit_rate, 4), unit='ratio',
                         policy=policy, kill_at='none', requests=24,
                         new_tokens=8, workload_spec=pspec.hash,
                         tokens_per_sec=round(res.tokens_per_sec, 2),
                         completed_ratio=round(res.completed_ratio, 4)))
    return rows


def bench_supervisor_recovery(on_tpu):
    """Elastic-supervisor MTTR rung (ISSUE 14): a journaled PS shard is
    snapshotted, hard-killed, and recovered by the ShardSupervisor
    (restart on the same endpoint -> restore newest snapshot -> replay
    the client journal). The value is the recover() walltime — liveness
    miss to shard serving restored state — which the regression gate
    checks LOWER-is-better ('mttr' in the metric name). Exactly-once is
    asserted inline: the replayed rows must match the pre-kill state
    bit-for-bit, with dedup hits covering every snapshot-covered entry.
    """
    import os
    import tempfile
    from paddle_tpu.distributed.ps.embedding_service import (
        EmbeddingClient, EmbeddingServer)
    from paddle_tpu.distributed.supervisor import (PushJournal, ShardSpec,
                                                   ShardSupervisor)
    from paddle_tpu.testing import chaos

    dim, n_ids, pushes = 16, 256, 8
    snap_dir = tempfile.mkdtemp(prefix='bench_sup_')

    def make_server(port=0):
        s = EmbeddingServer(port=port)
        s.create_table(0, dim=dim, optimizer='sgd', lr=0.1)
        s.start()
        return s

    srv = make_server()
    port = srv.port
    journal = PushJournal('bench-trainer')
    cli = EmbeddingClient(endpoints=['127.0.0.1:%d' % port],
                          journal=journal)
    rng = np.random.RandomState(0)
    ids = list(range(n_ids))
    cli.pull(0, ids)
    for _ in range(pushes):
        cli.push(0, ids, rng.randn(n_ids, dim).astype(np.float32))

    sup = ShardSupervisor(miss_threshold=1, restart_budget=3,
                          ping_timeout=0.5)
    sup.add_shard(ShardSpec('emb0', '127.0.0.1:%d' % port, role='ps',
                            restart=lambda: make_server(port) and None,
                            snapshot_dir=snap_dir, clients=(cli,)))
    sup.snapshot_all()
    # post-snapshot writes: the recovery must replay exactly these
    for _ in range(2):
        cli.push(0, ids, rng.randn(n_ids, dim).astype(np.float32))
    want = cli.pull(0, ids)

    chaos.kill_server(srv)
    t0 = time.time()
    sup.poll()                      # detects the miss and recovers
    mttr = time.time() - t0
    got = cli.pull(0, ids)
    if not np.array_equal(want, got):
        raise AssertionError('recovered shard state diverged')

    return [{'metric': 'supervisor_mttr_seconds', 'value': round(mttr, 4),
             'unit': 's', 'shard': 'embedding', 'rows': n_ids,
             'journal_replayed': journal.replayed,
             'journal_dedup_hits': journal.dedup_hits,
             'degraded': not on_tpu}]


def bench_capacity_calibration(on_tpu):
    """Capacity-simulator calibration rung (ISSUE 16): replay a small
    Poisson trace through a real 1-replica in-proc gateway, fit the
    two-parameter service model from its wide events, re-run the SAME
    trace through the discrete-event simulator, and report the TTFT
    divergence (max of p50/p99 relative error — the regression gate
    checks it LOWER-is-better; K-S statistic rides along as a field).

    A second, ungated-by-measurement row answers the acceptance
    question directly: a million-request synthetic sweep under a PINNED
    service model (so the reported minimum-replica answer is
    deterministic run to run), with the measured model's answer as an
    informational field.
    """
    import paddle_tpu as paddle
    from paddle_tpu.capacity import simulator, workload
    from paddle_tpu.capacity.replay import measure as replay_measure
    from paddle_tpu.monitor.registry import MetricRegistry
    from paddle_tpu.serving import ContinuousBatchingEngine
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dropout=0.0)
        lens, mnt, n_req = (32, 64, 96, 128), 64, 32
        max_len, chunk, block, num_slots = 256, 32, 8, 8
        mean_gap = 0.02
    else:
        # the bench_serving CPU regime: decode-GEMM-bound, service-bound
        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        lens, mnt, n_req = (8, 16, 24, 32), 32, 24
        max_len, chunk, block, num_slots = 64, 32, 8, 8
        mean_gap = 0.002
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    spec = _serving_workload(n_req, lens, mnt, mean_gap, cfg.vocab_size)
    trace = spec.generate()

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=num_slots, max_len=max_len,
            prefill_chunk=chunk, decode_block=block)

    reg = MetricRegistry()
    real_events, res = replay_measure(
        factory, trace, replicas=1, max_new_tokens=mnt, registry=reg)
    fitted = simulator.ServiceModel.from_events(
        real_events, prefill_chunk=chunk, decode_block=block,
        num_slots=num_slots, trace=trace, replicas=1)
    sim = simulator.simulate(trace, fitted, replicas=1,
                             router='least_loaded', registry=reg)
    div = simulator.compare_events(sim.to_events(), real_events)['overall']
    rows = [{'metric': 'capacity_sim_ttft_divergence',
             'value': round(max(div['p50_rel_err'], div['p99_rel_err']), 4),
             'unit': 'rel_err', 'trace': 'poisson',
             'mean_gap_s': mean_gap, 'requests': n_req,
             'new_tokens': mnt, 'num_slots': num_slots, 'replicas': 1,
             'workload_spec': spec.hash,
             'ks': round(div['ks'], 4),
             'p50_rel_err': round(div['p50_rel_err'], 4),
             'p99_rel_err': round(div['p99_rel_err'], 4),
             'sim_p50_ms': round(div['sim_p50_s'] * 1e3, 3),
             'real_p50_ms': round(div['real_p50_s'] * 1e3, 3),
             'sim_p99_ms': round(div['sim_p99_s'] * 1e3, 3),
             'real_p99_ms': round(div['real_p99_s'] * 1e3, 3),
             'service_model': fitted.to_dict(),
             'replay_tokens_per_sec': round(res.tokens_per_sec, 2),
             'degraded': not on_tpu}]

    # million-request sweep under a pinned model: the reported
    # minimum-replica answer must be deterministic for the gate
    big = workload.WorkloadSpec(
        requests=1000000, seed=0,
        arrival={'process': 'diurnal', 'mean_gap_s': 0.0005,
                 'period_s': 120.0, 'peak_to_trough': 4.0},
        lengths={'dist': 'zipf', 'a': 1.8, 'min': 8, 'max': 256},
        output={'dist': 'lognormal', 'median': 12, 'sigma': 0.5,
                'min': 1, 'max': 64},
        tenants={'mode': 'zipf', 'count': 20, 'a': 1.5})
    pinned = simulator.ServiceModel(0.002, 0.004, prefill_chunk=chunk,
                                    decode_block=block,
                                    num_slots=num_slots)
    sweep = simulator.sweep_replicas(big.generate(), pinned,
                                     counts=(8, 16, 32), slo_ttft_s=0.25)
    measured_min = simulator.sweep_replicas(
        trace, fitted, counts=(1, 2, 4),
        slo_ttft_s=10 * div['real_p99_s'])['min_replicas']
    rows.append({'metric': 'capacity_sweep_min_replicas',
                 'value': sweep['min_replicas'], 'unit': 'replicas',
                 'requests': sweep['requests'],
                 'slo_ttft_s': sweep['slo_ttft_s'],
                 'workload_spec': big.hash,
                 'sweep_points': sweep['points'],
                 'sweep_wall_s': round(sum(p['sim_wall_s']
                                           for p in sweep['points']), 3),
                 'measured_model_min_replicas': measured_min,
                 'service_model': pinned.to_dict(),
                 'degraded': not on_tpu})
    return rows


def bench_ingest(on_tpu):
    """Streaming-ingestion rung (ISSUE 18): the async double-buffered
    IngestPipeline vs the repo's synchronous baseline — io.DataLoader
    doing sampler-driven random access over the SAME disk-resident
    shard set — feeding an identical device step.

    The baseline is what training disk-resident data looked like before
    the ingestion plane: DataLoader(shuffle=True) indexes records one at
    a time (ShardReader.at pays the strided seek + skip every access)
    and nothing overlaps the step. The pipeline streams shards
    sequentially, window-shuffles, and prefetches batch k+1 while step
    k runs. The device step is calibrated to the pipeline's measured
    producer cost (the balance point where overlap matters most) and
    emulated host-idle on CPU (time.sleep — a dispatched TPU step keeps
    the host free, which one CPU core cannot also fake with real
    compute); on TPU it is a real jitted matmul stack.

    Gated rows: ingest_examples_per_sec (async, higher-is-better) with
    the DataLoader-sync and pipeline-sync numbers + speedups as fields,
    and ingest_data_wait_frac (async, lower-is-better) with the sync
    fraction alongside — near-zero async data_wait is the point.
    """
    import bisect
    import shutil
    import tempfile
    import jax
    import jax.numpy as jnp
    from paddle_tpu.data import write_shards, IngestPipeline
    from paddle_tpu.data.shards import ShardReader, decode_sample
    from paddle_tpu.io import DataLoader, Dataset
    from paddle_tpu.monitor.registry import MetricRegistry

    if on_tpu:
        n_records, n_shards, batch, dim, window = 65536, 8, 512, 256, 4096
    else:
        n_records, n_shards, batch, dim, window = 8192, 4, 256, 128, 1024
    tmp = tempfile.mkdtemp(prefix='bench_ingest_')
    try:
        rng = np.random.RandomState(0)
        paths = write_shards(
            ({'x': rng.randn(dim).astype(np.float32),
              'y': np.int64(i % 10)} for i in range(n_records)),
            tmp, n_shards)

        class ShardDataset(Dataset):
            """Random-access view the synchronous baseline indexes."""

            def __init__(self):
                self.readers = [ShardReader(p, decode=decode_sample)
                                for p in paths]
                self.cum = list(np.cumsum([r.records
                                           for r in self.readers]))

            def __len__(self):
                return self.cum[-1]

            def __getitem__(self, i):
                s = bisect.bisect_right(self.cum, i)
                return self.readers[s].at(
                    i - (self.cum[s - 1] if s else 0))

        def pipeline(prefetch):
            return IngestPipeline(paths, batch_size=batch,
                                  shuffle_window=window, seed=0,
                                  prefetch=prefetch, device_put=on_tpu,
                                  registry=MetricRegistry())

        # producer-only epoch: read + decode + shuffle + collate — the
        # per-batch input cost, which also calibrates the device step
        p = pipeline(0)
        t0 = time.time()
        n_batches = sum(1 for _ in p)
        step_s = (time.time() - t0) / max(n_batches, 1)

        if on_tpu:
            w = jnp.asarray(rng.randn(dim, dim).astype(np.float32) * 0.01)

            @jax.jit
            def unit_step(x, w):
                return jnp.tanh(x @ w).sum()

            x0 = jnp.zeros((batch, dim), jnp.float32)
            unit_step(x0, w).block_until_ready()        # compile
            t0 = time.time()
            for _ in range(8):
                unit_step(x0, w).block_until_ready()
            repeats = max(1, int(round(step_s * 8 / (time.time() - t0))))

            def device_step(b):
                for _ in range(repeats):
                    out = unit_step(b['x']._data, w)
                out.block_until_ready()
        else:
            repeats = 0

            def device_step(b):
                time.sleep(step_s)

        def drive_pipeline(prefetch):
            pipe = pipeline(prefetch)
            t0 = time.time()
            for b in pipe:
                device_step(b)
            wall = time.time() - t0
            return n_records / wall, pipe.last_epoch_stats[
                'data_wait_frac'], wall

        def drive_dataloader():
            loader = DataLoader(ShardDataset(), batch_size=batch,
                                shuffle=True, num_workers=0)
            t0 = time.time()
            wait = 0.0
            it = iter(loader)
            while True:
                w0 = time.time()
                try:
                    b = next(it)
                except StopIteration:
                    break
                wait += time.time() - w0
                device_step(b)
            wall = time.time() - t0
            return n_records / wall, wait / wall, wall

        drive_pipeline(0)                               # warm the path
        dl_eps, dl_wait, dl_wall = drive_dataloader()
        sync_eps, sync_wait, sync_wall = drive_pipeline(0)
        async_eps, async_wait, async_wall = drive_pipeline(2)

        base = {'unit': 'examples/sec', 'records': n_records,
                'shards': n_shards, 'batch': batch, 'dim': dim,
                'shuffle_window': window, 'prefetch': 2,
                'baseline': 'random_access_dataloader',
                'step_s': round(step_s, 6), 'step_repeats': repeats,
                'degraded': not on_tpu}
        return [
            dict(base, metric='ingest_examples_per_sec',
                 value=round(async_eps, 2),
                 dataloader_sync_examples_per_sec=round(dl_eps, 2),
                 pipeline_sync_examples_per_sec=round(sync_eps, 2),
                 speedup_vs_dataloader=round(async_eps / dl_eps, 3),
                 speedup_vs_pipeline_sync=round(async_eps / sync_eps, 3),
                 async_wall_s=round(async_wall, 4),
                 sync_wall_s=round(sync_wall, 4),
                 dataloader_wall_s=round(dl_wall, 4),
                 # rides on the throughput row so perf_report's bench
                 # table surfaces input-boundedness alongside examples/s
                 data_wait_frac=round(async_wait, 4)),
            dict(base, metric='ingest_data_wait_frac',
                 value=round(async_wait, 4), unit='ratio',
                 pipeline_sync_data_wait_frac=round(sync_wait, 4),
                 dataloader_data_wait_frac=round(dl_wait, 4)),
        ]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    try:
        _enable_cache()
    except Exception:
        pass
    on_tpu = _platform() == 'tpu'
    for fn in (bench_resnet, bench_yolo_infer, bench_gpt_decode,
               bench_serving, bench_serving_paged, bench_serving_gateway,
               bench_serving_gateway_tenants, bench_serving_gateway_qos,
               bench_serving_gateway_multimodel, bench_serving_fabric,
               bench_supervisor_recovery, bench_capacity_calibration,
               bench_ingest):
        try:
            res = fn(on_tpu)
            for row in (res if isinstance(res, list) else [res]):
                print(json.dumps(row))
        except Exception as e:  # never die half-way
            print(json.dumps({'metric': fn.__name__, 'error': repr(e)[:300]}))


if __name__ == '__main__':
    main()
